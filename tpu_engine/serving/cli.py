"""CLI entry points, argv-compatible with the reference binaries.

Reference launch lines work verbatim with ``python -m tpu_engine.serving.cli``
(or the ``bin/worker_node`` / ``bin/gateway`` wrappers):

  worker_node <port> <node_id> [model_path]     (worker_node.cpp:145-168;
                                                 $MODEL_PATH honored)
  gateway <worker1:port> [worker2:port] ...     (gateway.cpp:161-171)

Plus the TPU-native combined mode the reference doesn't have:

  serve [--model resnet50] [--lanes N] [--port 8000]
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _run_forever(stoppables=()):
    """Block until SIGTERM/SIGINT, then DRAIN instead of dying mid-request:
    the HTTP front stops accepting first, then each lane's batcher/decode
    scheduler joins (in-flight work resolves its futures). The reference's
    only shutdown is an abrupt kill (README.md:322 tests fault tolerance
    by exactly that)."""
    import signal
    import threading

    ev = threading.Event()

    def _handle(_signum, _frame):
        ev.set()

    try:
        signal.signal(signal.SIGTERM, _handle)
        signal.signal(signal.SIGINT, _handle)
    except ValueError:
        pass  # non-main thread (embedding); fall back to sleep loop
    try:
        while not ev.is_set():
            ev.wait(3600)
    except KeyboardInterrupt:
        pass
    # Second signal = force quit: restore default handlers so an operator
    # isn't locked out of Ctrl+C while a drain (or a hung lane) runs.
    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGINT, signal.SIG_DFL)
    except ValueError:
        pass
    for s in stoppables:
        try:
            s.stop()
        except Exception:
            pass


def _add_autoscale_flags(parser) -> None:
    """Elastic-fleet flags shared by ``gateway`` and ``serve`` (DESIGN.md
    "Elastic fleet"). All default to None so only explicitly-set flags
    reach GatewayConfig — defaults stay wire-byte-identical."""
    parser.add_argument("--autoscale", action="store_true",
                        help="closed-loop elastic fleet: a controller "
                             "thread reads per-lane overload pressure "
                             "and spawns/retires lanes against it — "
                             "scale-down drains via live stream "
                             "migration (zero tokens lost), scale-up "
                             "registers only after a passing /health "
                             "probe (implies --migrate-streams)")
    parser.add_argument("--autoscale-interval", type=float, default=None,
                        help="control-loop tick interval seconds "
                             "(default 1)")
    parser.add_argument("--autoscale-min-lanes", type=int, default=None,
                        help="never drain the fleet below this many "
                             "lanes (default 1)")
    parser.add_argument("--autoscale-max-lanes", type=int, default=None,
                        help="never spawn above this many lanes "
                             "(default 0 = provider capacity rules)")
    parser.add_argument("--autoscale-up-pressure", type=float,
                        default=None,
                        help="mean fleet pressure above which a lane "
                             "is spawned (default 0.75)")
    parser.add_argument("--autoscale-down-pressure", type=float,
                        default=None,
                        help="mean fleet pressure below which a lane "
                             "is retired (default 0.25)")
    parser.add_argument("--autoscale-cooldown", type=float, default=None,
                        help="minimum seconds between actuated "
                             "decisions (default 5)")
    parser.add_argument("--autoscale-spawn-timeout", type=float,
                        default=None,
                        help="a spawned lane that has not probed "
                             "healthy within this window is destroyed "
                             "and the fleet enters the named "
                             "spawn-wedged degraded state (default 30)")
    parser.add_argument("--autoscale-rebalance-band", type=float,
                        default=None,
                        help="role-rebalance arm (needs --disagg): flip "
                             "a lane prefill<->decode when the "
                             "prefill:decode pressure ratio leaves this "
                             "band, re-arming inside band/2 "
                             "(default 0 = off; must be > 1)")


def _apply_autoscale_flags(args, gw_kw: dict) -> None:
    if args.autoscale:
        gw_kw["autoscale"] = True
        # Scale-down must ride the live-migration ladder — without it,
        # retiring a lane sheds its streams onto the replay resume as
        # the PLAN rather than the last rung.
        gw_kw["migrate_streams"] = True
    if args.autoscale_interval is not None:
        gw_kw["autoscale_interval_s"] = args.autoscale_interval
    if args.autoscale_min_lanes is not None:
        gw_kw["autoscale_min_lanes"] = args.autoscale_min_lanes
    if args.autoscale_max_lanes is not None:
        gw_kw["autoscale_max_lanes"] = args.autoscale_max_lanes
    if args.autoscale_up_pressure is not None:
        gw_kw["autoscale_up_pressure"] = args.autoscale_up_pressure
    if args.autoscale_down_pressure is not None:
        gw_kw["autoscale_down_pressure"] = args.autoscale_down_pressure
    if args.autoscale_cooldown is not None:
        gw_kw["autoscale_cooldown_s"] = args.autoscale_cooldown
    if args.autoscale_spawn_timeout is not None:
        gw_kw["autoscale_spawn_timeout_s"] = args.autoscale_spawn_timeout
    if args.autoscale_rebalance_band is not None:
        gw_kw["autoscale_rebalance_band"] = args.autoscale_rebalance_band


def _add_slo_flags(parser) -> None:
    """Observability-plane gateway flags shared by ``gateway`` and
    ``serve`` (DESIGN.md "Observability plane"). All default to None /
    off so defaults stay wire-byte-identical."""
    parser.add_argument("--trace-stitch", action="store_true",
                        help="cross-lane trace stitching: propagate each "
                             "stream's trace context through every "
                             "mobility hop (handoff, migration, crash "
                             "resume) and keep a stream ledger so "
                             "GET /admin/trace/<request_id> returns ONE "
                             "merged Perfetto tree covering every lane "
                             "the stream touched")
    parser.add_argument("--trace-ledger-capacity", type=int, default=None,
                        help="streams the stitch ledger remembers "
                             "(FIFO eviction; default 512)")
    parser.add_argument("--slo-ttft-p99-ms", type=float, default=None,
                        help="TTFT latency objective in ms: --slo-target "
                             "of first tokens must land under this; "
                             "burn rate surfaces at /admin/slo, /stats "
                             "and tpu_engine_slo_* (0/unset = off)")
    parser.add_argument("--slo-itl-p99-ms", type=float, default=None,
                        help="inter-token latency objective in ms "
                             "(0/unset = off)")
    parser.add_argument("--slo-completion-p99-ms", type=float,
                        default=None,
                        help="full request-completion latency objective "
                             "in ms, measured at gateway scope — "
                             "failover/handoff/migration time included "
                             "(0/unset = off)")
    parser.add_argument("--slo-target", type=float, default=None,
                        help="good-sample fraction the objectives "
                             "demand (default 0.99; error budget = "
                             "1 - target)")
    parser.add_argument("--slo-window-s", type=float, default=None,
                        help="sliding burn-rate window seconds "
                             "(default 300)")
    parser.add_argument("--autoscale-slo-feed", action="store_true",
                        help="feed SLO burn into the elastic-fleet "
                             "controller: fleet pressure becomes "
                             "max(lane pressure, worst burn / 2) — the "
                             "feed only ever ADDS pressure (needs "
                             "--autoscale and an --slo-* objective)")


def _apply_slo_flags(args, gw_kw: dict) -> None:
    if args.trace_stitch:
        gw_kw["trace_stitch"] = True
    if args.trace_ledger_capacity is not None:
        gw_kw["trace_ledger_capacity"] = args.trace_ledger_capacity
    if args.slo_ttft_p99_ms is not None:
        gw_kw["slo_ttft_p99_ms"] = args.slo_ttft_p99_ms
    if args.slo_itl_p99_ms is not None:
        gw_kw["slo_itl_p99_ms"] = args.slo_itl_p99_ms
    if args.slo_completion_p99_ms is not None:
        gw_kw["slo_completion_p99_ms"] = args.slo_completion_p99_ms
    if args.slo_target is not None:
        gw_kw["slo_target"] = args.slo_target
    if args.slo_window_s is not None:
        gw_kw["slo_window_s"] = args.slo_window_s
    if args.autoscale_slo_feed:
        gw_kw["autoscale_slo_feed"] = True


def _add_flight_flags(parser) -> None:
    """Observability-plane worker flags shared by ``worker_node`` and
    ``serve``: the per-tick flight recorder and the jax.profiler
    capture directory."""
    parser.add_argument("--profile-dir", type=str, default=None,
                        help="jax.profiler capture directory: arms "
                             "POST /admin/profile {\"ticks\": N} to "
                             "trace exactly N scheduler ticks into "
                             "this dir (TensorBoard/Perfetto; "
                             "unset = profiling refused)")
    parser.add_argument("--flight-recorder", type=int, default=None,
                        help="per-tick flight recorder: keep a ring of "
                             "this many per-tick scheduler records "
                             "(GET /admin/timeline), auto-dumped to a "
                             "postmortem JSON on anomaly — recover, "
                             "deadline-miss burst, degraded fleet "
                             "state (0/unset = off)")
    parser.add_argument("--flight-dump-dir", type=str, default=None,
                        help="directory for flight-recorder postmortem "
                             "dumps (unset = dumps stay in-memory, "
                             "visible via /admin/timeline last_dump)")


def _apply_flight_flags(args, gen_kw: dict) -> None:
    if args.profile_dir is not None:
        gen_kw["profile_dir"] = args.profile_dir
    if args.flight_recorder is not None:
        gen_kw["flight_recorder"] = args.flight_recorder
    if args.flight_dump_dir is not None:
        gen_kw["flight_dump_dir"] = args.flight_dump_dir


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return 2
    cmd, rest = argv[0], argv[1:]

    # TPU_ENGINE_PLATFORM=cpu runs serving on the host backend (e.g. several
    # worker processes on one machine, reference-style, when the TPU chip is
    # single-tenant). The axon plugin ignores JAX_PLATFORMS, hence the knob.
    platform = os.environ.get("TPU_ENGINE_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    if cmd in ("worker", "worker_node"):
        from tpu_engine.serving.app import model_from_path, serve_worker
        from tpu_engine.utils.config import WorkerConfig

        if not rest:
            print("Usage: worker_node <port> <node_id> [model_path] "
                  "[--kv-block-size N] [--kv-blocks N] "
                  "[--kv-host-blocks N] [--kv-quantize int8] "
                  "[--step-chunk N] "
                  "[--prefill-chunk N] [--scheduler-stall-s S]")
            return 1
        parser = argparse.ArgumentParser(prog="worker_node")
        parser.add_argument("port", type=int)
        parser.add_argument("node_id", nargs="?", default=None)
        parser.add_argument("model_arg", nargs="?", default=None)
        # Optional generation knobs so a STANDALONE worker (the unit the
        # `gateway` command routes across, and the unit the chaos harness
        # kill -9s) can serve the same paged/continuous configuration as
        # combined mode — the positional reference argv stays verbatim.
        parser.add_argument("--kv-block-size", type=int, default=None,
                            help="paged KV block size (0/unset = dense)")
        parser.add_argument("--kv-blocks", type=int, default=None,
                            help="paged KV pool size in blocks (0 = auto)")
        parser.add_argument("--kv-host-blocks", type=int, default=None,
                            help="hierarchical host-RAM KV tier: demote "
                                 "cold radix prefixes to this many pinned "
                                 "host blocks and swap them back in on a "
                                 "radix hit instead of recomputing "
                                 "(0/unset = off)")
        parser.add_argument("--kv-quantize", default=None,
                            choices=("int8",),
                            help="store paged KV block payloads int8 with "
                                 "per-(slot, kv-head) f32 scales — ~2x "
                                 "blocks on the same HBM; requires "
                                 "--kv-block-size (unset = bf16 pool)")
        parser.add_argument("--state-rows", type=int, default=None,
                            help="recurrent state slab pool capacity in "
                                 "rows (state_slab-family models, e.g. "
                                 "mamba2: one fixed-size row per live "
                                 "stream, constant in sequence length; "
                                 "0/unset = auto)")
        parser.add_argument("--tp", type=int, default=None,
                            help="tensor-parallel serving: shard the "
                                 "model (registry-declared partition "
                                 "rule) and the paged KV pool's H_kv "
                                 "axis over this many local devices — "
                                 "one SPMD ragged dispatch per tick; "
                                 "needs --kv-block-size; unshardable "
                                 "families (mamba2) refuse at startup "
                                 "(unset/1 = single-device)")
        parser.add_argument("--step-chunk", type=int, default=None,
                            help="decode chunk length per dispatch")
        parser.add_argument("--prefill-chunk", type=int, default=None,
                            help="prefill chunk width")
        parser.add_argument("--scheduler-stall-s", type=float, default=None,
                            help="decode-loop liveness threshold: /health "
                                 "reads unhealthy when the loop has not "
                                 "ticked for this long (0/unset = report "
                                 "age only)")
        parser.add_argument("--priority-admission", action="store_true",
                            help="shed lowest-priority-tier first under "
                                 "depth pressure (requests carry "
                                 "priority: interactive|batch|background)")
        parser.add_argument("--adaptive-depth", action="store_true",
                            help="AIMD adaptive concurrency limit driven "
                                 "by observed latency vs the "
                                 "sliding-window baseline")
        parser.add_argument("--brownout", action="store_true",
                            help="staged brownout controller: degrade "
                                 "gracefully (budget shrink, spec off, "
                                 "swap-in deferral, low-tier clamp) "
                                 "before shedding")
        parser.add_argument("--role", default=None,
                            choices=("prefill", "decode", "both"),
                            help="disaggregated serving role (needs "
                                 "--kv-block-size for dedicated roles): "
                                 "a role-aware gateway (--disagg) lands "
                                 "fresh generate work on prefill lanes "
                                 "and ships finished KV chains to "
                                 "decode lanes; flippable at runtime "
                                 "via /admin/role (default: both = "
                                 "today's colocated behavior)")
        parser.add_argument("--trace-stitch", action="store_true",
                            help="cross-lane trace stitching (worker "
                                 "side): exported row snapshots and KV "
                                 "chains carry the stream's trace "
                                 "context so the importing lane's spans "
                                 "join the same tree")
        parser.add_argument("--prefix-fetch", action="store_true",
                            help="fleet prefix tier (worker side; needs "
                                 "--kv-block-size + prefix sharing): "
                                 "serve /admin/export_prefix to peers, "
                                 "publish bounded radix summaries in "
                                 "/health, and pull a gateway-hinted "
                                 "peer's KV chain before prefilling a "
                                 "local radix miss — every failure "
                                 "falls back to local prefill")
        parser.add_argument("--prefix-fetch-timeout", type=float,
                            default=None,
                            help="per-fetch peer budget in seconds "
                                 "(default 5)")
        parser.add_argument("--prefix-fetch-inflight", type=int,
                            default=None,
                            help="concurrent outbound peer fetches per "
                                 "lane; excess misses prefill locally "
                                 "(default 2)")
        parser.add_argument("--no-unified-stateless", action="store_true",
                            help="retire the unified stateless lane: "
                                 "route /predict misses and /score "
                                 "through the legacy dedicated batch "
                                 "processor instead of single-tick rows "
                                 "in the continuous scheduler (default: "
                                 "unified — one slot pool, one set of "
                                 "deadlines/brownout/counters for every "
                                 "request class)")
        _add_flight_flags(parser)
        args = parser.parse_args(rest)
        port = args.port
        node_id = args.node_id or f"worker_{port}"
        model_arg = args.model_arg or os.environ.get("MODEL_PATH", "resnet50")
        # A real path loads real weights (HF/torch/orbax via the worker's
        # _load_model_path); a bare registry name serves random init. HF
        # checkpoint dirs resolve their registry model from config.json
        # (e.g. model_type "resnet" → resnet50-v1, the importable family).
        model_path = model_arg if os.path.exists(model_arg) else None
        model = None
        if model_path and model_path.endswith(".onnx"):
            model = "onnx"  # architecture comes from the file (onnx_graph)
        elif model_path:
            sidecar = os.path.join(model_path, "tpu_engine_model.json")
            if os.path.isdir(model_path) and os.path.exists(sidecar):
                # Self-describing orbax checkpoint (train CLI writes it).
                import json

                with open(sidecar) as f:
                    model = json.load(f)["model"]
            else:
                from tpu_engine.models.import_weights import (
                    model_name_from_hf,
                )

                model = model_name_from_hf(model_path)
        gen_kw = {}
        if args.kv_block_size is not None:
            gen_kw["gen_kv_block_size"] = args.kv_block_size
        if args.kv_blocks is not None:
            gen_kw["gen_kv_blocks"] = args.kv_blocks
        if args.kv_host_blocks is not None:
            gen_kw["gen_kv_host_blocks"] = args.kv_host_blocks
        if args.kv_quantize is not None:
            gen_kw["gen_kv_quantize"] = args.kv_quantize
        if args.state_rows is not None:
            gen_kw["gen_state_rows"] = args.state_rows
        if args.tp is not None:
            gen_kw["tp"] = args.tp
        if args.step_chunk is not None:
            gen_kw["gen_step_chunk"] = args.step_chunk
        if args.prefill_chunk is not None:
            gen_kw["gen_prefill_chunk"] = args.prefill_chunk
        if args.scheduler_stall_s is not None:
            gen_kw["scheduler_stall_s"] = args.scheduler_stall_s
        if args.priority_admission:
            gen_kw["priority_admission"] = True
        if args.adaptive_depth:
            gen_kw["adaptive_depth"] = True
        if args.brownout:
            gen_kw["brownout"] = True
        if args.role is not None:
            gen_kw["role"] = args.role
        if args.trace_stitch:
            gen_kw["trace_stitch"] = True
        if args.prefix_fetch:
            gen_kw["gen_prefix_fetch"] = True
        if args.prefix_fetch_timeout is not None:
            gen_kw["gen_prefix_fetch_timeout_s"] = args.prefix_fetch_timeout
        if args.prefix_fetch_inflight is not None:
            gen_kw["gen_prefix_fetch_inflight"] = args.prefix_fetch_inflight
        if args.no_unified_stateless:
            gen_kw["unified_stateless"] = False
        _apply_flight_flags(args, gen_kw)
        cfg = WorkerConfig(port=port, node_id=node_id,
                           model=model or model_from_path(model_arg),
                           model_path=model_path, **gen_kw)
        worker, server = serve_worker(cfg, background=True)
        _run_forever([server, worker])
        return 0

    if cmd == "gateway":
        from tpu_engine.serving.app import serve_gateway
        from tpu_engine.utils.config import GatewayConfig

        if not rest:
            print("Usage: gateway <worker1_host:port> [worker2_host:port] ...")
            return 1
        parser = argparse.ArgumentParser(prog="gateway")
        parser.add_argument("workers", nargs="+")
        parser.add_argument("--port", type=int, default=8000)
        parser.add_argument("--breaker-timeout", type=float, default=30.0,
                            help="circuit-breaker OPEN->HALF_OPEN timeout "
                                 "seconds (reference gateway.cpp:22)")
        parser.add_argument("--failover-streams", action="store_true",
                            help="crash-tolerant streaming: journal "
                                 "/generate/stream token events and resume "
                                 "a mid-stream worker failure on another "
                                 "ring lane, splicing one seamless "
                                 "byte-identical stream (default: the "
                                 "stream terminates with an error event)")
        parser.add_argument("--health-probe-interval", type=float,
                            default=0.0,
                            help="proactive lane health prober: GET each "
                                 "worker's /health at this interval and "
                                 "eject lanes from routing after 3 "
                                 "consecutive failures, restoring them on "
                                 "recovery (seconds; 0 = off)")
        parser.add_argument("--migrate-streams", action="store_true",
                            help="live stream migration: graceful removal "
                                 "(remove_worker drain) EXPORTS each "
                                 "in-flight /generate/stream's KV block "
                                 "chain + state off the draining lane and "
                                 "resumes it mid-stream on another lane "
                                 "with zero re-prefilled tokens (any "
                                 "failure falls back to the replay "
                                 "resume; implies the stream journal)")
        parser.add_argument("--migrate-timeout", type=float, default=None,
                            help="per-stream migration transfer budget in "
                                 "seconds, clamped to the stream's "
                                 "original deadline (default 30)")
        parser.add_argument("--drain-timeout", type=float, default=None,
                            help="graceful-drain acknowledgment bound in "
                                 "seconds: a wedged lane's drain call is "
                                 "abandoned (counted) and removal "
                                 "proceeds (default 10)")
        parser.add_argument("--retry-budget", type=float, default=None,
                            help="global retry budget: failover retries "
                                 "(stream resumes included) capped at this "
                                 "fraction of recent requests "
                                 "(default: unlimited)")
        parser.add_argument("--prefix-affinity", action="store_true",
                            help="route /generate(+/stream) on a "
                                 "block-aligned prompt-prefix fingerprint "
                                 "instead of request_id: shared prefixes "
                                 "converge on the lane whose radix tree "
                                 "already holds the KV blocks (ring-order "
                                 "fallback under ejection/imbalance)")
        parser.add_argument("--affinity-block-size", type=int, default=None,
                            help="fingerprint block granularity — MUST "
                                 "match the workers' --kv-block-size "
                                 "(default 16)")
        parser.add_argument("--affinity-prefix-blocks", type=int,
                            default=None,
                            help="leading blocks the fingerprint covers "
                                 "(default 4)")
        parser.add_argument("--affinity-max-imbalance", type=int,
                            default=None,
                            help="skip the affinity lane (ring order) once "
                                 "it is this many recent dispatches hotter "
                                 "than its least-loaded peer (0 = always "
                                 "honor affinity)")
        parser.add_argument("--prefix-directory", action="store_true",
                            help="fleet prefix tier (gateway side): keep "
                                 "a bounded fingerprint->owner-lane "
                                 "directory (prober /health summaries + "
                                 "post-completion updates) and stamp "
                                 "generate-class dispatches with a "
                                 "prefix_hint so --prefix-fetch lanes "
                                 "can pull the owner's KV chain instead "
                                 "of re-prefilling it (works with "
                                 "affinity off)")
        parser.add_argument("--prefix-dir-capacity", type=int,
                            default=None,
                            help="directory LRU bound in entries "
                                 "(default 512)")
        parser.add_argument("--overload-control", action="store_true",
                            help="priority-tiered gateway admission "
                                 "(lowest tier sheds first as "
                                 "--overload-max-inflight fills) + "
                                 "load-derived Retry-After on sheds")
        parser.add_argument("--overload-max-inflight", type=int,
                            default=None,
                            help="gateway in-flight gauge for tier "
                                 "admission (0 = no gauge)")
        parser.add_argument("--tenant-rate", type=float, default=None,
                            help="per-tenant token-bucket rate limit "
                                 "(requests/s; 0 = off)")
        parser.add_argument("--disagg", action="store_true",
                            help="disaggregated prefill/decode serving: "
                                 "while the fleet has dedicated "
                                 "--role prefill lanes, /generate(+/"
                                 "stream) lands on a prefill lane and "
                                 "the finished KV chain ships to a "
                                 "decode lane picked by load (zero "
                                 "re-prefilled tokens; every failure "
                                 "falls back to local decode or the "
                                 "replay resume)")
        parser.add_argument("--handoff-timeout", type=float, default=None,
                            help="per-stream prefill→decode handoff "
                                 "budget in seconds, clamped to the "
                                 "stream's deadline (default 30)")
        _add_autoscale_flags(parser)
        _add_slo_flags(parser)
        parser.add_argument("--standby-worker", action="append",
                            default=None, metavar="HOST:PORT",
                            help="pre-launched worker ADDRESS for the "
                                 "elastic fleet's warm standby pool "
                                 "(repeatable); joins the ring only "
                                 "when the autoscaler scales up and its "
                                 "/health probe passes")
        args = parser.parse_args(rest)
        gw_kw = {}
        if args.overload_control:
            gw_kw["overload_control"] = True
        if args.overload_max_inflight is not None:
            gw_kw["overload_max_inflight"] = args.overload_max_inflight
        if args.tenant_rate is not None:
            gw_kw["tenant_rate"] = args.tenant_rate
        if args.retry_budget is not None:
            gw_kw["retry_budget_ratio"] = args.retry_budget
        if args.migrate_streams:
            gw_kw["migrate_streams"] = True
        _apply_autoscale_flags(args, gw_kw)
        _apply_slo_flags(args, gw_kw)
        if args.migrate_timeout is not None:
            gw_kw["migrate_timeout_s"] = args.migrate_timeout
        if args.drain_timeout is not None:
            gw_kw["drain_timeout_s"] = args.drain_timeout
        if args.prefix_affinity:
            gw_kw["prefix_affinity"] = True
        if args.affinity_block_size is not None:
            gw_kw["affinity_block_size"] = args.affinity_block_size
        if args.affinity_prefix_blocks is not None:
            gw_kw["affinity_prefix_blocks"] = args.affinity_prefix_blocks
        if args.affinity_max_imbalance is not None:
            gw_kw["affinity_max_imbalance"] = args.affinity_max_imbalance
        if args.prefix_directory:
            gw_kw["prefix_directory"] = True
        if args.prefix_dir_capacity is not None:
            gw_kw["prefix_directory_capacity"] = args.prefix_dir_capacity
        if args.disagg:
            gw_kw["disagg"] = True
        if args.handoff_timeout is not None:
            gw_kw["handoff_timeout_s"] = args.handoff_timeout
        gw, server = serve_gateway(
            args.workers,
            GatewayConfig(port=args.port,
                          breaker_timeout_s=args.breaker_timeout,
                          failover_streams=args.failover_streams,
                          health_probe_interval_s=args.health_probe_interval,
                          **gw_kw),
            background=True,
            standby_workers=args.standby_worker)
        _run_forever([server, gw])
        return 0

    if cmd == "serve":
        from tpu_engine.serving.app import serve_combined

        parser = argparse.ArgumentParser(prog="serve")
        parser.add_argument("--model", default="resnet50")
        parser.add_argument("--model-path", default=None,
                            help="HF/torch/orbax checkpoint with real weights "
                                 "(default: random init)")
        parser.add_argument("--lanes", type=int, default=0)
        parser.add_argument("--mesh", default=None,
                            help="mesh-sharded serving: one engine spanning "
                                 "all chips, e.g. data=8 or model=2,data=4 "
                                 "(batch scatter / TP weights over ICI)")
        parser.add_argument("--port", type=int, default=8000)
        parser.add_argument("--warmup", action="store_true",
                            help="pre-compile all batch buckets before listening")
        parser.add_argument("--shape-buckets", default=None,
                            help="mixed-shape serving: comma-separated HxWxC "
                                 "list, e.g. 320x320x3,640x640x3")
        parser.add_argument("--batch-buckets", default=None,
                            help="comma-separated batch sizes to compile, "
                                 "e.g. 1,8,32,128 (default 1..32; larger "
                                 "buckets raise MFU on throughput-bound "
                                 "fleets — batch 32 is the reference "
                                 "batcher's cap, not the chip's)")
        parser.add_argument("--pipeline-depth", type=int, default=None,
                            help="submitted batches kept in flight on the "
                                 "miss path (default 4); raise when the "
                                 "dispatch round-trip dwarfs the device "
                                 "step (high-latency links)")
        parser.add_argument("--cache-capacity", type=int, default=None,
                            help="result-cache entries per lane (default "
                                 "1000, reference worker_node.cpp:33)")
        parser.add_argument("--batch-timeout-ms", type=float, default=None,
                            help="dynamic batcher flush timeout (default "
                                 "20, reference worker_node.cpp:36)")
        parser.add_argument("--breaker-timeout", type=float, default=None,
                            help="circuit-breaker OPEN->HALF_OPEN timeout "
                                 "seconds (default 30, reference gateway.cpp:22)")
        # -- resilience layer (DESIGN.md "Request resilience"; every knob
        # defaults off/permissive = reference-faithful behavior) ---------
        parser.add_argument("--default-deadline-ms", type=float, default=None,
                            help="deadline applied to requests without a "
                                 "deadline_ms field; expired requests shed "
                                 "503 + Retry-After instead of queueing "
                                 "(default: no deadline)")
        parser.add_argument("--retry-budget", type=float, default=None,
                            help="global retry budget: failover retries "
                                 "capped at this fraction of recent "
                                 "requests, e.g. 0.1 (default: unlimited)")
        parser.add_argument("--retry-backoff-ms", type=float, default=None,
                            help="base exponential backoff between failover "
                                 "attempts, with +/-50%% jitter (default 0 "
                                 "= immediate ring-order march)")
        parser.add_argument("--hedge", action="store_true",
                            help="hedged dispatch for idempotent ops: when "
                                 "the primary lane exceeds the hedge "
                                 "latency quantile, fire the next lane and "
                                 "take the first response")
        parser.add_argument("--hedge-quantile", type=float, default=None,
                            help="latency quantile that arms a hedge "
                                 "(default 0.95)")
        parser.add_argument("--hedge-min-ms", type=float, default=None,
                            help="floor under the hedge threshold; also "
                                 "the threshold until enough samples "
                                 "(default 50)")
        parser.add_argument("--max-queue-depth", type=int, default=None,
                            help="per-lane admission cap: concurrent "
                                 "requests beyond this shed 503 "
                                 "(default 0 = unbounded)")
        # -- adaptive overload control (DESIGN.md "Overload control";
        # every knob defaults off = behavior above unchanged) ------------
        parser.add_argument("--overload-control", action="store_true",
                            help="gateway overload control: "
                                 "priority-tiered admission (requests "
                                 "carry priority: interactive | batch | "
                                 "background; lowest tier sheds first "
                                 "as --overload-max-inflight fills) and "
                                 "load-derived Retry-After on sheds")
        parser.add_argument("--overload-max-inflight", type=int,
                            default=None,
                            help="gateway in-flight gauge the tier "
                                 "fractions admit against (background "
                                 "sheds at 70%%, batch at 85%%, "
                                 "interactive at 100%%; 0 = no gauge)")
        parser.add_argument("--tenant-rate", type=float, default=None,
                            help="per-tenant token bucket: each tenant "
                                 "(request \"tenant\" key) sustains this "
                                 "many requests/s; excess sheds 503 with "
                                 "the bucket's refill time as "
                                 "Retry-After (0 = off)")
        parser.add_argument("--tenant-burst", type=float, default=None,
                            help="token-bucket depth per tenant "
                                 "(default 0 = auto: 2x rate)")
        parser.add_argument("--priority-admission", action="store_true",
                            help="worker lanes shed lowest-priority-tier "
                                 "first under depth pressure (tier "
                                 "fractions of the lane's concurrency "
                                 "limit)")
        parser.add_argument("--adaptive-depth", action="store_true",
                            help="AIMD adaptive concurrency limit per "
                                 "lane: replaces the static "
                                 "--max-queue-depth cap with a limit "
                                 "driven by observed latency vs the "
                                 "sliding-window baseline")
        parser.add_argument("--brownout", action="store_true",
                            help="staged brownout: a per-lane control "
                                 "loop reads saturation signals (tick "
                                 "age, queue depth, pool starvation, "
                                 "deadline misses) and degrades "
                                 "gracefully — shrink the mixed token "
                                 "budget, suspend speculation, defer "
                                 "host-tier swap-ins, clamp low-tier "
                                 "token budgets — before any shed, "
                                 "restoring in reverse as pressure "
                                 "clears")
        parser.add_argument("--brownout-clamp-tokens", type=int,
                            default=None,
                            help="stage-4 max_new_tokens ceiling for "
                                 "below-top-tier generate requests "
                                 "(default 32)")
        parser.add_argument("--failover-streams", action="store_true",
                            help="crash-tolerant streaming: journal "
                                 "/generate/stream token events and resume "
                                 "a mid-stream lane failure on another "
                                 "ring lane (prompt + emitted tokens, "
                                 "budget offset), splicing one seamless "
                                 "byte-identical stream")
        parser.add_argument("--migrate-streams", action="store_true",
                            help="live stream migration: graceful lane "
                                 "removal exports each in-flight stream's "
                                 "KV block chain + state and resumes it "
                                 "mid-stream on another lane with zero "
                                 "re-prefilled tokens (failures fall back "
                                 "to the replay resume; implies the "
                                 "stream journal)")
        parser.add_argument("--migrate-timeout", type=float, default=None,
                            help="per-stream migration transfer budget in "
                                 "seconds, clamped to the stream's "
                                 "original deadline (default 30)")
        parser.add_argument("--drain-timeout", type=float, default=None,
                            help="graceful-drain acknowledgment bound in "
                                 "seconds: a wedged lane's drain call is "
                                 "abandoned (counted) and removal "
                                 "proceeds (default 10)")
        parser.add_argument("--health-probe-interval", type=float,
                            default=None,
                            help="proactive lane health prober: probe each "
                                 "lane's health at this interval, ejecting "
                                 "lanes after 3 consecutive failures and "
                                 "restoring them on recovery (seconds; "
                                 "default off)")
        parser.add_argument("--scheduler-stall-s", type=float, default=None,
                            help="decode-loop liveness threshold: a "
                                 "continuous scheduler whose loop has not "
                                 "ticked for this long reads unhealthy in "
                                 "/health (wedged-device detection; set "
                                 "above the worst first-request compile; "
                                 "default off — age is reported either "
                                 "way)")
        parser.add_argument("--native-front", choices=["auto", "on", "off"],
                            default="auto",
                            help="serving edge: the C++ HttpFront when "
                                 "available (auto), required (on), or the "
                                 "Python front (off — required for "
                                 "incremental SSE streaming granularity; "
                                 "the C++ front ships a stream as one "
                                 "buffered body)")
        parser.add_argument("--gen-scheduler",
                            choices=["batch", "continuous", "speculative"],
                            default="continuous",
                            help="decode scheduling: continuous "
                                 "(iteration-level admission; measured 7.4x "
                                 "tokens/s under Poisson arrivals, "
                                 "BENCH_r04_builder.json), "
                                 "batch-to-completion, or speculative "
                                 "(draft-model proposals verified by the "
                                 "target in one windowed pass; temperature "
                                 "sampling only)")
        parser.add_argument("--gen-draft-model", default=None,
                            help="draft model for --gen-scheduler "
                                 "speculative (default: auto, e.g. "
                                 "gpt2 -> distilgpt2)")
        parser.add_argument("--gen-draft-path", default=None,
                            help="draft model weights checkpoint")
        parser.add_argument("--gen-spec-k", type=int, default=4,
                            help="speculation depth: draft tokens proposed "
                                 "per verify round")
        parser.add_argument("--gen-decode-fused", action="store_true",
                            help="batch scheduler: whole decode loop as "
                                 "one dispatch (zero per-chunk host "
                                 "syncs; identical streams)")
        parser.add_argument("--no-unified-stateless", action="store_true",
                            help="retire the unified stateless lane: "
                                 "route /predict misses and /score "
                                 "through the legacy dedicated batch "
                                 "processor instead of single-tick rows "
                                 "in the continuous scheduler (default: "
                                 "unified — one slot pool, one set of "
                                 "deadlines/brownout/counters for every "
                                 "request class)")
        parser.add_argument("--gen-prefill-chunk", type=int, default=256,
                            help="chunked prefill window (continuous "
                                 "scheduler): longer prompts admit in "
                                 "window dispatches so decode interleaves "
                                 "(0 disables)")
        parser.add_argument("--gen-prefix-cache-mb", type=int, default=64,
                            help="continuous-scheduler prefix cache budget "
                                 "(device KV MB; repeated prompts skip "
                                 "prefill; 0 disables)")
        parser.add_argument("--kv-block-size", type=int, default=0,
                            help="paged KV cache (continuous scheduler): "
                                 "columns per block, e.g. 16 or 32. Rows "
                                 "reserve blocks for the tokens they hold "
                                 "instead of max_seq each — several times "
                                 "more concurrent rows at the same HBM. "
                                 "0 (default) keeps the dense cache")
        parser.add_argument("--kv-blocks", type=int, default=0,
                            help="paged pool size in blocks (0 = auto: "
                                 "the dense layout's capacity)")
        parser.add_argument("--kv-host-blocks", type=int, default=0,
                            help="hierarchical host-RAM KV tier (needs "
                                 "--kv-block-size + prefix sharing): LRU "
                                 "eviction demotes cold radix prefixes to "
                                 "this many pinned host-RAM blocks, and a "
                                 "radix hit on a demoted prefix swaps the "
                                 "blocks back in asynchronously instead "
                                 "of recomputing its prefill — host RAM "
                                 "becomes prefix-cache capacity "
                                 "(bench.py --scenario affinity-ab). "
                                 "0 = off")
        parser.add_argument("--kv-quantize", default="",
                            choices=("", "int8"),
                            help="quantized KV blocks (needs "
                                 "--kv-block-size): store block payloads "
                                 "int8 with per-(slot, kv-head) f32 "
                                 "scales, quantized once at block write "
                                 "and dequantized inside the paged "
                                 "attention read — ~2x blocks on the same "
                                 "HBM (bench.py --scenario quant-ab). "
                                 "Greedy streams stay deterministic but "
                                 "are not byte-identical to the bf16 "
                                 "pool. Default off = today's pool")
        parser.add_argument("--state-rows", type=int, default=0,
                            help="recurrent state slab pool capacity in "
                                 "rows (state_slab-family models, e.g. "
                                 "mamba2/ssd-small-test: each live "
                                 "stream owns ONE fixed-size "
                                 "(n_layers, state_dim) f32 row for its "
                                 "whole life — peak concurrent rows are "
                                 "independent of sequence length, "
                                 "bench.py --scenario recurrent-ab. "
                                 "0 = auto: decode slots + 1)")
        parser.add_argument("--tp", type=int, default=None,
                            help="tensor-parallel serving (needs "
                                 "--kv-block-size): every lane serves "
                                 "the model sharded over this many "
                                 "local devices on a `model`-axis mesh "
                                 "— registry-declared param placement, "
                                 "H_kv-sharded KV pool, one SPMD "
                                 "ragged dispatch per tick (bench.py "
                                 "--scenario tp-ab); default lane "
                                 "count becomes devices//tp; "
                                 "unshardable families (mamba2) "
                                 "refuse at startup (unset/1 = "
                                 "single-device lanes)")
        parser.add_argument("--prefix-affinity", action="store_true",
                            help="gateway: route /generate(+/stream) on a "
                                 "block-aligned prompt-prefix fingerprint "
                                 "instead of request_id so shared prefixes "
                                 "converge on the lane whose radix tree "
                                 "already holds the blocks; falls back to "
                                 "ring order when the affinity lane is "
                                 "ejected, broken, or imbalanced")
        parser.add_argument("--affinity-block-size", type=int, default=None,
                            help="fingerprint block granularity (defaults "
                                 "to --kv-block-size when paged, else 16)")
        parser.add_argument("--affinity-prefix-blocks", type=int,
                            default=None,
                            help="leading blocks the fingerprint covers "
                                 "(default 4)")
        parser.add_argument("--affinity-max-imbalance", type=int,
                            default=None,
                            help="skip the affinity lane (ring order) once "
                                 "it is this many recent dispatches hotter "
                                 "than its least-loaded ring peer "
                                 "(default 0 = always honor affinity)")
        parser.add_argument("--prefix-sharing", choices=["on", "off"],
                            default="on",
                            help="block-level radix prefix sharing (paged "
                                 "mode): shared prompt prefixes reuse "
                                 "already-filled KV blocks and skip their "
                                 "prefill compute")
        parser.add_argument("--prefix-fetch", action="store_true",
                            help="fleet-wide prefix tier (needs "
                                 "--kv-block-size + prefix sharing): the "
                                 "gateway keeps a fingerprint->owner-lane "
                                 "directory and stamps generate dispatches "
                                 "with a prefix_hint; a lane admitting a "
                                 "local radix miss pulls the owner's KV "
                                 "chain peer-to-peer (checksum-verified) "
                                 "instead of re-prefilling — every "
                                 "failure falls back to local prefill "
                                 "(bench.py --scenario fleet-prefix-ab)")
        parser.add_argument("--prefix-fetch-timeout", type=float,
                            default=None,
                            help="per-fetch peer budget in seconds "
                                 "(default 5)")
        parser.add_argument("--mixed-step", action="store_true",
                            help="mixed prefill+decode stepping (needs "
                                 "--kv-block-size): every scheduler tick "
                                 "issues ONE ragged dispatch serving decode "
                                 "rows (1 token each) and admitting rows' "
                                 "prefill chunks together — long prompts "
                                 "stop spiking in-flight rows' inter-token "
                                 "latency (bench.py --scenario mixed-ab)")
        parser.add_argument("--mixed-token-budget", type=int, default=0,
                            help="new tokens per mixed tick (decode rows "
                                 "count 1 each; the rest splits over "
                                 "admitting rows' chunks and caps the "
                                 "compiled chunk width). 0 = auto "
                                 "(--gen-prefill-chunk)")
        parser.add_argument("--spec-k", type=int, default=0,
                            help="continuous speculative decoding (needs "
                                 "--kv-block-size; composes with "
                                 "--mixed-step): a drafter proposes up to "
                                 "this many tokens per decode row per tick "
                                 "and the tick's ONE ragged dispatch "
                                 "verifies every window — rows advance "
                                 "1..k+1 tokens per dispatch, greedy "
                                 "streams byte-identical to plain decode "
                                 "(bench.py --scenario spec-ab). 0 = off")
        parser.add_argument("--spec-draft", choices=["ngram", "model"],
                            default="ngram",
                            help="drafter for --spec-k: ngram = host-side "
                                 "prompt-lookup (no second model, no extra "
                                 "dispatches; default), model = greedy "
                                 "proposals from --gen-draft-model (one "
                                 "draft dispatch per drafted row per tick)")
        parser.add_argument("--quantize", choices=["int8"], default=None,
                            help="weight-only quantization: dense/conv "
                                 "kernels stored int8 with per-channel "
                                 "scales (halves weight HBM traffic)")
        parser.add_argument("--role", default="both",
                            choices=("prefill", "decode", "both"),
                            help="serving role for EVERY lane (see "
                                 "--lane-roles for a split in-process "
                                 "fleet; dedicated roles need "
                                 "--kv-block-size)")
        parser.add_argument("--lane-roles", default=None,
                            help="disaggregated in-process fleet: "
                                 "comma-separated per-lane roles, e.g. "
                                 "prefill,prefill,decode,decode "
                                 "(assigned round-robin; overrides "
                                 "--role; pair with --disagg)")
        parser.add_argument("--disagg", action="store_true",
                            help="role-aware gateway: land fresh "
                                 "/generate(+/stream) work on prefill "
                                 "lanes and ship each finished KV chain "
                                 "to a decode lane picked by load — "
                                 "zero re-prefilled tokens, every "
                                 "failure falls back to local decode "
                                 "or the replay resume (bench.py "
                                 "--scenario disagg-ab)")
        parser.add_argument("--handoff-timeout", type=float, default=None,
                            help="per-stream prefill→decode handoff "
                                 "budget in seconds, clamped to the "
                                 "stream's deadline (default 30)")
        _add_autoscale_flags(parser)
        _add_slo_flags(parser)
        _add_flight_flags(parser)
        args = parser.parse_args(rest)
        gw_kw = {}
        if args.breaker_timeout is not None:
            gw_kw["breaker_timeout_s"] = args.breaker_timeout
        if args.default_deadline_ms is not None:
            gw_kw["default_deadline_ms"] = args.default_deadline_ms
        if args.retry_budget is not None:
            gw_kw["retry_budget_ratio"] = args.retry_budget
        if args.retry_backoff_ms is not None:
            gw_kw["retry_backoff_base_ms"] = args.retry_backoff_ms
        if args.hedge:
            gw_kw["hedge_enabled"] = True
        if args.hedge_quantile is not None:
            gw_kw["hedge_quantile"] = args.hedge_quantile
        if args.hedge_min_ms is not None:
            gw_kw["hedge_min_ms"] = args.hedge_min_ms
        if args.failover_streams:
            gw_kw["failover_streams"] = True
        if args.migrate_streams:
            gw_kw["migrate_streams"] = True
        if args.migrate_timeout is not None:
            gw_kw["migrate_timeout_s"] = args.migrate_timeout
        if args.drain_timeout is not None:
            gw_kw["drain_timeout_s"] = args.drain_timeout
        if args.health_probe_interval is not None:
            gw_kw["health_probe_interval_s"] = args.health_probe_interval
        if args.overload_control:
            gw_kw["overload_control"] = True
        if args.overload_max_inflight is not None:
            gw_kw["overload_max_inflight"] = args.overload_max_inflight
        if args.tenant_rate is not None:
            gw_kw["tenant_rate"] = args.tenant_rate
        if args.tenant_burst is not None:
            gw_kw["tenant_burst"] = args.tenant_burst
        if args.prefix_affinity:
            gw_kw["prefix_affinity"] = True
            # Fingerprint granularity defaults to the lanes' actual block
            # size — a mismatched pair would converge requests that share
            # no reusable blocks (or scatter ones that do).
            if args.affinity_block_size is not None:
                gw_kw["affinity_block_size"] = args.affinity_block_size
            elif args.kv_block_size > 0:
                gw_kw["affinity_block_size"] = args.kv_block_size
            if args.affinity_prefix_blocks is not None:
                gw_kw["affinity_prefix_blocks"] = args.affinity_prefix_blocks
            if args.affinity_max_imbalance is not None:
                gw_kw["affinity_max_imbalance"] = args.affinity_max_imbalance
        if args.prefix_fetch:
            # One flag arms BOTH halves in combined mode: the gateway's
            # directory + hint stamping and the lanes' peer fetch path.
            gw_kw["prefix_directory"] = True
            # The directory fingerprints at the lanes' REAL block size
            # even with affinity routing off — a mismatched granularity
            # would promise chains the radix trees don't share at.
            if "affinity_block_size" not in gw_kw and args.kv_block_size > 0:
                gw_kw["affinity_block_size"] = args.kv_block_size
        if args.disagg:
            gw_kw["disagg"] = True
        if args.handoff_timeout is not None:
            gw_kw["handoff_timeout_s"] = args.handoff_timeout
        _apply_autoscale_flags(args, gw_kw)
        _apply_slo_flags(args, gw_kw)
        gateway_config = None
        if gw_kw:
            from tpu_engine.utils.config import GatewayConfig

            gateway_config = GatewayConfig(port=args.port, **gw_kw)
        from tpu_engine.utils.config import WorkerConfig

        buckets = None
        if args.shape_buckets:
            buckets = tuple(
                tuple(int(d) for d in s.split("x"))
                for s in args.shape_buckets.split(","))
        bb_kw = {}
        if args.batch_buckets:
            bb_kw["batch_buckets"] = tuple(
                int(b) for b in args.batch_buckets.split(","))
            # The batcher flushes at the largest bucket — otherwise a
            # bigger compiled bucket could never fill.
            bb_kw["max_batch_size"] = max(bb_kw["batch_buckets"])
        if args.pipeline_depth is not None:
            bb_kw["pipeline_depth"] = args.pipeline_depth
        if args.cache_capacity is not None:
            bb_kw["cache_capacity"] = args.cache_capacity
        if args.batch_timeout_ms is not None:
            bb_kw["batch_timeout_ms"] = args.batch_timeout_ms
        if args.max_queue_depth is not None:
            bb_kw["max_queue_depth"] = args.max_queue_depth
        if args.tp is not None:
            bb_kw["tp"] = args.tp
        if args.scheduler_stall_s is not None:
            bb_kw["scheduler_stall_s"] = args.scheduler_stall_s
        if args.priority_admission:
            bb_kw["priority_admission"] = True
        if args.adaptive_depth:
            bb_kw["adaptive_depth"] = True
        if args.brownout:
            bb_kw["brownout"] = True
        if args.brownout_clamp_tokens is not None:
            bb_kw["brownout_clamp_tokens"] = args.brownout_clamp_tokens
        # One --trace-stitch flag arms BOTH halves in combined mode: the
        # gateway's ledger + payload injection and the lanes' snapshot /
        # chain trace headers.
        if args.trace_stitch:
            bb_kw["trace_stitch"] = True
        if args.prefix_fetch:
            bb_kw["gen_prefix_fetch"] = True
        if args.prefix_fetch_timeout is not None:
            bb_kw["gen_prefix_fetch_timeout_s"] = args.prefix_fetch_timeout
        if args.no_unified_stateless:
            bb_kw["unified_stateless"] = False
        _apply_flight_flags(args, bb_kw)
        worker_config = WorkerConfig(shape_buckets=buckets, **bb_kw,
                                     gen_scheduler=args.gen_scheduler,
                                     gen_draft_model=args.gen_draft_model,
                                     gen_draft_path=args.gen_draft_path,
                                     gen_spec_k=args.gen_spec_k,
                                     gen_prefix_cache_mb=args.gen_prefix_cache_mb,
                                     gen_prefill_chunk=args.gen_prefill_chunk,
                                     gen_kv_block_size=args.kv_block_size,
                                     gen_kv_blocks=args.kv_blocks,
                                     gen_kv_host_blocks=args.kv_host_blocks,
                                     gen_kv_quantize=args.kv_quantize,
                                     gen_prefix_sharing=(
                                         args.prefix_sharing == "on"),
                                     gen_mixed_step=args.mixed_step,
                                     gen_mixed_token_budget=(
                                         args.mixed_token_budget),
                                     gen_continuous_spec_k=args.spec_k,
                                     gen_state_rows=args.state_rows,
                                     gen_spec_draft=args.spec_draft,
                                     gen_decode_fused=args.gen_decode_fused,
                                     quantize=args.quantize,
                                     role=args.role,
                                     model_path=args.model_path)
        native_front = {"auto": None, "on": True, "off": False}[
            args.native_front]
        lane_roles = None
        if args.lane_roles:
            lane_roles = [r.strip() for r in args.lane_roles.split(",")
                          if r.strip()]
        gw, workers, server = serve_combined(
            model=args.model, lanes=args.lanes, port=args.port,
            warmup=args.warmup, worker_config=worker_config,
            gateway_config=gateway_config, mesh=args.mesh,
            native_front=native_front, lane_roles=lane_roles)
        _run_forever([server, *workers, gw])
        return 0

    if cmd == "import-weights":
        # HF/torch checkpoint → orbax checkpoint serving artifact:
        #   import-weights --model gpt2 --src /path/to/hf_ckpt --out ckpt/
        # The orbax output then serves via `worker_node <port> <id> ckpt/`.
        parser = argparse.ArgumentParser(prog="import-weights")
        parser.add_argument("--model", required=True,
                            help="registry model name (gpt2, bert, resnet50-v1)")
        parser.add_argument("--src", required=True,
                            help="HF checkpoint dir, .safetensors, or torch .bin")
        parser.add_argument("--out", required=True)
        args = parser.parse_args(rest)
        from tpu_engine.models.import_weights import load_pretrained
        from tpu_engine.utils.checkpoint import save_params

        params = load_pretrained(args.model, args.src)
        path = save_params(args.out, params)
        print(f"imported {args.src} as {args.model} -> {path}")
        return 0

    if cmd == "train":
        # Causal-LM fine-tune loop (the reference is inference-only; the
        # TPU-native framework's sharded apply drives training too):
        #   train --model gpt2-small-test --steps 50 --out ckpt/
        #   train --mesh data=2,model=4 --remat ...       (sharded + remat)
        #   train --resume ckpt/state --out ckpt/         (exact resume)
        # Writes orbax train state to <out>/state and bare params to
        # <out>/params — the latter serves directly:
        #   worker_node 8001 w1 <out>/params
        parser = argparse.ArgumentParser(prog="train")
        parser.add_argument("--model", default="gpt2-small-test",
                            help="registry decoder LM (needs a "
                                 "TransformerConfig)")
        parser.add_argument("--steps", type=int, default=50)
        parser.add_argument("--batch", type=int, default=8)
        parser.add_argument("--seq", type=int, default=None,
                            help="train sequence length (default: the "
                                 "model's max_seq)")
        parser.add_argument("--lr", type=float, default=1e-3)
        parser.add_argument("--mesh", default=None,
                            help="e.g. data=2,model=4 — params TP-shard "
                                 "over model, batch over data; axis sizes "
                                 "must multiply to the local device count "
                                 "(pure DP on 8 chips: data=8)")
        parser.add_argument("--remat", action="store_true",
                            help="jax.checkpoint each block (activation "
                                 "HBM ~ one layer instead of all L)")
        parser.add_argument("--data", default=None,
                            help=".npy int32 token array (N, seq+1); "
                                 "default: a fixed synthetic batch "
                                 "(memorization smoke)")
        parser.add_argument("--out", default=None,
                            help="checkpoint dir (state + params)")
        parser.add_argument("--resume", default=None,
                            help="train-state dir to resume from")
        parser.add_argument("--log-every", type=int, default=10)
        parser.add_argument("--seed", type=int, default=0)
        args = parser.parse_args(rest)

        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from tpu_engine.models.registry import (
            _ensure_builtin_models_imported,
            create_model,
        )
        from tpu_engine.models.transformer import (
            TransformerConfig,
            transformer_apply,
        )
        from tpu_engine.training.train import (
            cross_entropy_loss,
            make_train_step,
            shard_params_tp,
        )
        from tpu_engine.utils.checkpoint import (
            load_train_state,
            save_params,
            save_train_state,
        )

        _ensure_builtin_models_imported()
        spec = create_model(args.model)
        cfg = spec.config
        if not isinstance(cfg, TransformerConfig) or not cfg.causal:
            print(f"'{args.model}' is not a causal-LM transformer")
            return 2
        seq = min(args.seq or cfg.max_seq, cfg.max_seq)

        def apply_fn(params, x, dtype=jnp.bfloat16):
            return transformer_apply(params, x.astype(jnp.int32), cfg,
                                     dtype=dtype, remat=args.remat)

        init_state, train_step = make_train_step(
            apply_fn, loss_fn=cross_entropy_loss,
            optimizer=optax.adamw(args.lr), dtype=jnp.float32)
        params = spec.init(jax.random.PRNGKey(args.seed))

        mesh = None
        if args.mesh:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from tpu_engine.serving.app import parse_mesh_spec

            mesh = parse_mesh_spec(args.mesh)

        def place(tree):
            """TP-shard 2-D kernels over `model` when the mesh has that
            axis (pure-DP meshes replicate params; the batch still shards
            over `data`)."""
            if mesh is None:
                return tree
            if "model" in mesh.shape:
                return jax.device_put(
                    tree, shard_params_tp(tree, mesh, "model"))
            return jax.device_put(
                tree, jax.tree.map(lambda _l: NamedSharding(mesh, P()),
                                   tree))

        params = place(params)
        state = jax.jit(init_state)(params)
        if args.resume:
            # load_train_state restores host arrays; re-place the WHOLE
            # state (opt_state mirrors the param tree) or a sharded mesh
            # run would silently train on full replicated copies.
            state = place(load_train_state(args.resume, like=state))
            print(f"resumed at step {int(state.step)}")

        if args.data:
            tokens = np.load(args.data).astype(np.int32)
            assert tokens.ndim == 2 and tokens.shape[1] >= seq + 1, \
                f"need (N, >= {seq + 1}) tokens, got {tokens.shape}"
        else:  # fixed synthetic batch: loss falling = the loop works
            tokens = np.random.default_rng(args.seed).integers(
                1, cfg.vocab, (args.batch, seq + 1)).astype(np.int32)

        jitted = jax.jit(train_step, donate_argnums=(0,))
        rng = np.random.default_rng(args.seed + 1)
        max_off = tokens.shape[1] - (seq + 1)
        for k in range(args.steps):
            rows = (rng.integers(0, tokens.shape[0], args.batch)
                    if args.data else np.arange(args.batch))
            # Random column offset: long --data documents train on every
            # window, not just their first seq+1 tokens.
            off = int(rng.integers(0, max_off + 1)) if max_off > 0 else 0
            window = tokens[rows, off:off + seq + 1]
            x = jnp.asarray(window[:, :-1], jnp.float32)
            y = jnp.asarray(window[:, 1:], jnp.int32)
            if mesh is not None:
                x = jax.device_put(x, NamedSharding(mesh, P("data", None)))
                y = jax.device_put(y, NamedSharding(mesh, P("data", None)))
            state, loss = jitted(state, x, y)
            if k % args.log_every == 0 or k == args.steps - 1:
                print(f"step {int(state.step)}: loss {float(loss):.4f}",
                      flush=True)
        if args.out:
            import json

            spath = save_train_state(os.path.join(args.out, "state"), state,
                                     overwrite=True)
            ppath = save_params(os.path.join(args.out, "params"),
                                state.params, overwrite=True)
            # Self-describing checkpoint: worker_node resolves the
            # architecture from this sidecar, so the reference launch line
            # `worker_node <port> <id> <ckpt>/params` needs no model flag.
            with open(os.path.join(ppath, "tpu_engine_model.json"),
                      "w") as f:
                json.dump({"model": args.model}, f)
            print(f"saved train state -> {spath}")
            print(f"saved servable params -> {ppath}")
        return 0

    if cmd == "save-checkpoint":
        # Initialize a model's params and persist them — gives model_path
        # launch lines (reference worker_node.cpp:154-168) a real artifact.
        parser = argparse.ArgumentParser(prog="save-checkpoint")
        parser.add_argument("--model", required=True)
        parser.add_argument("--out", required=True)
        parser.add_argument("--seed", type=int, default=0)
        args = parser.parse_args(rest)
        import jax

        from tpu_engine.models.registry import create_model, _ensure_builtin_models_imported
        from tpu_engine.utils.checkpoint import save_params

        _ensure_builtin_models_imported()
        spec = create_model(args.model)
        params = spec.init(jax.random.PRNGKey(args.seed))
        path = save_params(args.out, params)
        print(f"saved {args.model} params -> {path}")
        return 0

    print(f"unknown command '{cmd}' "
          "(expected worker_node | gateway | serve | train | "
          "save-checkpoint | import-weights)")
    return 2


if __name__ == "__main__":
    sys.exit(main())
