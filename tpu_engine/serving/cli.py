"""CLI entry points, argv-compatible with the reference binaries.

Reference launch lines work verbatim with ``python -m tpu_engine.serving.cli``
(or the ``bin/worker_node`` / ``bin/gateway`` wrappers):

  worker_node <port> <node_id> [model_path]     (worker_node.cpp:145-168;
                                                 $MODEL_PATH honored)
  gateway <worker1:port> [worker2:port] ...     (gateway.cpp:161-171)

Plus the TPU-native combined mode the reference doesn't have:

  serve [--model resnet50] [--lanes N] [--port 8000]
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _run_forever(stoppables=()):
    """Block until SIGTERM/SIGINT, then DRAIN instead of dying mid-request:
    the HTTP front stops accepting first, then each lane's batcher/decode
    scheduler joins (in-flight work resolves its futures). The reference's
    only shutdown is an abrupt kill (README.md:322 tests fault tolerance
    by exactly that)."""
    import signal
    import threading

    ev = threading.Event()

    def _handle(_signum, _frame):
        ev.set()

    try:
        signal.signal(signal.SIGTERM, _handle)
        signal.signal(signal.SIGINT, _handle)
    except ValueError:
        pass  # non-main thread (embedding); fall back to sleep loop
    try:
        while not ev.is_set():
            ev.wait(3600)
    except KeyboardInterrupt:
        pass
    # Second signal = force quit: restore default handlers so an operator
    # isn't locked out of Ctrl+C while a drain (or a hung lane) runs.
    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGINT, signal.SIG_DFL)
    except ValueError:
        pass
    for s in stoppables:
        try:
            s.stop()
        except Exception:
            pass


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return 2
    cmd, rest = argv[0], argv[1:]

    # TPU_ENGINE_PLATFORM=cpu runs serving on the host backend (e.g. several
    # worker processes on one machine, reference-style, when the TPU chip is
    # single-tenant). The axon plugin ignores JAX_PLATFORMS, hence the knob.
    platform = os.environ.get("TPU_ENGINE_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    if cmd in ("worker", "worker_node"):
        from tpu_engine.serving.app import model_from_path, serve_worker
        from tpu_engine.utils.config import WorkerConfig

        if not rest:
            print("Usage: worker_node <port> <node_id> [model_path]")
            return 1
        port = int(rest[0])
        node_id = rest[1] if len(rest) > 1 else f"worker_{port}"
        model_arg = rest[2] if len(rest) > 2 else os.environ.get("MODEL_PATH", "resnet50")
        # A real path loads real weights (HF/torch/orbax via the worker's
        # _load_model_path); a bare registry name serves random init. HF
        # checkpoint dirs resolve their registry model from config.json
        # (e.g. model_type "resnet" → resnet50-v1, the importable family).
        model_path = model_arg if os.path.exists(model_arg) else None
        model = None
        if model_path and model_path.endswith(".onnx"):
            model = "onnx"  # architecture comes from the file (onnx_graph)
        elif model_path:
            from tpu_engine.models.import_weights import model_name_from_hf

            model = model_name_from_hf(model_path)
        cfg = WorkerConfig(port=port, node_id=node_id,
                           model=model or model_from_path(model_arg),
                           model_path=model_path)
        worker, server = serve_worker(cfg, background=True)
        _run_forever([server, worker])
        return 0

    if cmd == "gateway":
        from tpu_engine.serving.app import serve_gateway
        from tpu_engine.utils.config import GatewayConfig

        if not rest:
            print("Usage: gateway <worker1_host:port> [worker2_host:port] ...")
            return 1
        parser = argparse.ArgumentParser(prog="gateway")
        parser.add_argument("workers", nargs="+")
        parser.add_argument("--port", type=int, default=8000)
        parser.add_argument("--breaker-timeout", type=float, default=30.0,
                            help="circuit-breaker OPEN->HALF_OPEN timeout "
                                 "seconds (reference gateway.cpp:22)")
        args = parser.parse_args(rest)
        _gw, server = serve_gateway(
            args.workers,
            GatewayConfig(port=args.port,
                          breaker_timeout_s=args.breaker_timeout),
            background=True)
        _run_forever([server])
        return 0

    if cmd == "serve":
        from tpu_engine.serving.app import serve_combined

        parser = argparse.ArgumentParser(prog="serve")
        parser.add_argument("--model", default="resnet50")
        parser.add_argument("--model-path", default=None,
                            help="HF/torch/orbax checkpoint with real weights "
                                 "(default: random init)")
        parser.add_argument("--lanes", type=int, default=0)
        parser.add_argument("--mesh", default=None,
                            help="mesh-sharded serving: one engine spanning "
                                 "all chips, e.g. data=8 or model=2,data=4 "
                                 "(batch scatter / TP weights over ICI)")
        parser.add_argument("--port", type=int, default=8000)
        parser.add_argument("--warmup", action="store_true",
                            help="pre-compile all batch buckets before listening")
        parser.add_argument("--shape-buckets", default=None,
                            help="mixed-shape serving: comma-separated HxWxC "
                                 "list, e.g. 320x320x3,640x640x3")
        parser.add_argument("--batch-buckets", default=None,
                            help="comma-separated batch sizes to compile, "
                                 "e.g. 1,8,32,128 (default 1..32; larger "
                                 "buckets raise MFU on throughput-bound "
                                 "fleets — batch 32 is the reference "
                                 "batcher's cap, not the chip's)")
        parser.add_argument("--pipeline-depth", type=int, default=None,
                            help="submitted batches kept in flight on the "
                                 "miss path (default 4); raise when the "
                                 "dispatch round-trip dwarfs the device "
                                 "step (high-latency links)")
        parser.add_argument("--cache-capacity", type=int, default=None,
                            help="result-cache entries per lane (default "
                                 "1000, reference worker_node.cpp:33)")
        parser.add_argument("--batch-timeout-ms", type=float, default=None,
                            help="dynamic batcher flush timeout (default "
                                 "20, reference worker_node.cpp:36)")
        parser.add_argument("--breaker-timeout", type=float, default=None,
                            help="circuit-breaker OPEN->HALF_OPEN timeout "
                                 "seconds (default 30, reference gateway.cpp:22)")
        parser.add_argument("--gen-scheduler",
                            choices=["batch", "continuous", "speculative"],
                            default="continuous",
                            help="decode scheduling: continuous "
                                 "(iteration-level admission; measured 7.4x "
                                 "tokens/s under Poisson arrivals, "
                                 "BENCH_r04_builder.json), "
                                 "batch-to-completion, or speculative "
                                 "(draft-model proposals verified by the "
                                 "target in one windowed pass; temperature "
                                 "sampling only)")
        parser.add_argument("--gen-draft-model", default=None,
                            help="draft model for --gen-scheduler "
                                 "speculative (default: auto, e.g. "
                                 "gpt2 -> distilgpt2)")
        parser.add_argument("--gen-draft-path", default=None,
                            help="draft model weights checkpoint")
        parser.add_argument("--gen-spec-k", type=int, default=4,
                            help="speculation depth: draft tokens proposed "
                                 "per verify round")
        parser.add_argument("--gen-decode-fused", action="store_true",
                            help="batch scheduler: whole decode loop as "
                                 "one dispatch (zero per-chunk host "
                                 "syncs; identical streams)")
        parser.add_argument("--gen-prefill-chunk", type=int, default=256,
                            help="chunked prefill window (continuous "
                                 "scheduler): longer prompts admit in "
                                 "window dispatches so decode interleaves "
                                 "(0 disables)")
        parser.add_argument("--gen-prefix-cache-mb", type=int, default=64,
                            help="continuous-scheduler prefix cache budget "
                                 "(device KV MB; repeated prompts skip "
                                 "prefill; 0 disables)")
        parser.add_argument("--quantize", choices=["int8"], default=None,
                            help="weight-only quantization: dense/conv "
                                 "kernels stored int8 with per-channel "
                                 "scales (halves weight HBM traffic)")
        args = parser.parse_args(rest)
        gateway_config = None
        if args.breaker_timeout is not None:
            from tpu_engine.utils.config import GatewayConfig

            gateway_config = GatewayConfig(port=args.port,
                                           breaker_timeout_s=args.breaker_timeout)
        from tpu_engine.utils.config import WorkerConfig

        buckets = None
        if args.shape_buckets:
            buckets = tuple(
                tuple(int(d) for d in s.split("x"))
                for s in args.shape_buckets.split(","))
        bb_kw = {}
        if args.batch_buckets:
            bb_kw["batch_buckets"] = tuple(
                int(b) for b in args.batch_buckets.split(","))
            # The batcher flushes at the largest bucket — otherwise a
            # bigger compiled bucket could never fill.
            bb_kw["max_batch_size"] = max(bb_kw["batch_buckets"])
        if args.pipeline_depth is not None:
            bb_kw["pipeline_depth"] = args.pipeline_depth
        if args.cache_capacity is not None:
            bb_kw["cache_capacity"] = args.cache_capacity
        if args.batch_timeout_ms is not None:
            bb_kw["batch_timeout_ms"] = args.batch_timeout_ms
        worker_config = WorkerConfig(shape_buckets=buckets, **bb_kw,
                                     gen_scheduler=args.gen_scheduler,
                                     gen_draft_model=args.gen_draft_model,
                                     gen_draft_path=args.gen_draft_path,
                                     gen_spec_k=args.gen_spec_k,
                                     gen_prefix_cache_mb=args.gen_prefix_cache_mb,
                                     gen_prefill_chunk=args.gen_prefill_chunk,
                                     gen_decode_fused=args.gen_decode_fused,
                                     quantize=args.quantize,
                                     model_path=args.model_path)
        _gw, workers, server = serve_combined(
            model=args.model, lanes=args.lanes, port=args.port,
            warmup=args.warmup, worker_config=worker_config,
            gateway_config=gateway_config, mesh=args.mesh)
        _run_forever([server, *workers])
        return 0

    if cmd == "import-weights":
        # HF/torch checkpoint → orbax checkpoint serving artifact:
        #   import-weights --model gpt2 --src /path/to/hf_ckpt --out ckpt/
        # The orbax output then serves via `worker_node <port> <id> ckpt/`.
        parser = argparse.ArgumentParser(prog="import-weights")
        parser.add_argument("--model", required=True,
                            help="registry model name (gpt2, bert, resnet50-v1)")
        parser.add_argument("--src", required=True,
                            help="HF checkpoint dir, .safetensors, or torch .bin")
        parser.add_argument("--out", required=True)
        args = parser.parse_args(rest)
        from tpu_engine.models.import_weights import load_pretrained
        from tpu_engine.utils.checkpoint import save_params

        params = load_pretrained(args.model, args.src)
        path = save_params(args.out, params)
        print(f"imported {args.src} as {args.model} -> {path}")
        return 0

    if cmd == "save-checkpoint":
        # Initialize a model's params and persist them — gives model_path
        # launch lines (reference worker_node.cpp:154-168) a real artifact.
        parser = argparse.ArgumentParser(prog="save-checkpoint")
        parser.add_argument("--model", required=True)
        parser.add_argument("--out", required=True)
        parser.add_argument("--seed", type=int, default=0)
        args = parser.parse_args(rest)
        import jax

        from tpu_engine.models.registry import create_model, _ensure_builtin_models_imported
        from tpu_engine.utils.checkpoint import save_params

        _ensure_builtin_models_imported()
        spec = create_model(args.model)
        params = spec.init(jax.random.PRNGKey(args.seed))
        path = save_params(args.out, params)
        print(f"saved {args.model} params -> {path}")
        return 0

    print(f"unknown command '{cmd}' "
          "(expected worker_node | gateway | serve | save-checkpoint | "
          "import-weights)")
    return 2


if __name__ == "__main__":
    sys.exit(main())
