"""Elastic fleet: closed-loop autoscaling over the serving gateway.

DESIGN.md "Elastic fleet". Every primitive this controller composes
already exists in the serving stack — it closes the loop ROADMAP item 4
left open:

- **Control signal** (PR 9): per-lane overload pressure — AIMD adaptive
  depth limit, admission queue fill, brownout stage — read off each
  lane's ``/health``, folded into one mean fleet pressure in [0, ~1+].
- **Scale-down actuator** (PR 11): ``Gateway.remove_worker(drain=True)``
  — bounded graceful drain, then live stream migration off the retiring
  lane (KV chain over the wire, zero re-prefilled tokens). The replay
  resume is the ladder's last rung, never the plan.
- **Scale-up actuator**: probe-before-register — a spawned lane joins
  the rings ONLY after a passing ``/health`` probe, so the ring never
  routes to a lane that is still compiling or dead on arrival.
- **Role-rebalance arm** (PR 14): ``Gateway.set_worker_role`` — the
  drain + migrate + undrain role flip — driven by the observed
  prefill:decode pressure ratio with a hysteresis band.

The controller is crash-tolerant by construction: every decision is
idempotent (spawn of a member → ``already-member``; retire of a
non-member → ``unknown-lane``), every actuator is bounded by a timeout,
and a wedged actuator — a lane that will not drain, a spawn that never
turns healthy — lands the fleet in a NAMED degraded-but-serving state
(``drain-wedged`` / ``spawn-wedged``) instead of hanging the loop.
Every decision bumps a ``FleetCounters`` field AND drops a matching
``fleet`` marker span (counters == spans, chaos-asserted by
``tools/fault_injection.py --elastic``).

Engagement is ``--autoscale`` (default off: no controller thread, no
``/stats`` ``fleet`` block, wire bytes identical to the static fleet).
The ``/admin/fleet`` manual surface works either way — manual actions
run the same actuator ladders on an unstarted controller.
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
from typing import Dict, List, Optional

from tpu_engine.serving.clients import HttpWorkerClient

# Named degraded-but-serving states (DESIGN.md "Elastic fleet").
DEGRADED_SPAWN_WEDGED = "spawn-wedged"
DEGRADED_DRAIN_WEDGED = "drain-wedged"


def lane_pressure(health: dict) -> Optional[float]:
    """Fold one lane's ``/health`` body into a scalar pressure.

    Ladder (most-informative signal wins): AIMD adaptive limit (queue
    fill against the *adapted* depth), plain admission queue fill,
    decode-slot occupancy. An engaged brownout stage clamps the lane to
    saturated (>= 1.0) regardless — the lane is already degrading
    itself. ``None`` when the body carries no load signal at all (the
    caller drops the lane from the mean instead of reading it as idle).
    """
    if not isinstance(health, dict):
        return None
    p: Optional[float] = None
    adm = health.get("admission")
    if isinstance(adm, dict):
        depth = float(adm.get("queue_depth", 0) or 0)
        adaptive = adm.get("adaptive")
        limit = 0.0
        if isinstance(adaptive, dict):
            limit = float(adaptive.get("limit", 0) or 0)
        if limit <= 0:
            limit = float(adm.get("max_queue_depth", 0) or 0)
        if limit > 0:
            p = depth / limit
    if p is None:
        gen = health.get("generator")
        if isinstance(gen, dict):
            slots = float(gen.get("n_slots", 0) or 0)
            if slots > 0:
                p = float(gen.get("active", 0) or 0) / slots
    bo = health.get("brownout")
    if isinstance(bo, dict) and int(bo.get("stage", 0) or 0) > 0:
        p = max(p or 0.0, 1.0)
    return None if p is None else max(0.0, p)


class StandbyLaneProvider:
    """Warm standby pool: pre-launched worker ADDRESSES the controller
    checks out on scale-up and returns on scale-down. The classic
    chips-are-provisioned-but-idle elastic shape — spawn is instant
    (the probe gate still applies: a standby that died while parked
    never reaches the ring), retire hands the address back for the next
    ramp. Thread-safe; ``spawn`` returns ``None`` when the pool is dry."""

    def __init__(self, addresses: Optional[List[str]] = None):
        self._lock = threading.Lock()
        self._standby: List[str] = list(addresses or [])
        self._leased: set = set()

    def add(self, address: str) -> None:
        with self._lock:
            if address not in self._standby and address not in self._leased:
                self._standby.append(address)

    def spawn(self) -> Optional[str]:
        with self._lock:
            if not self._standby:
                return None
            addr = self._standby.pop(0)
            self._leased.add(addr)
            return addr

    def destroy(self, handle) -> None:
        """A lease that never turned healthy goes back to standby (the
        operator may revive the process; the probe gate re-screens it)."""
        self.retire(handle)

    def retire(self, handle) -> None:
        with self._lock:
            addr = str(handle)
            self._leased.discard(addr)
            if addr not in self._standby:
                self._standby.append(addr)

    def capacity(self) -> int:
        with self._lock:
            return len(self._standby)


class InProcessLaneProvider:
    """Spawn lanes as in-process worker objects from a factory —
    ``factory(index) -> WorkerNode``-like object with a ``node_id`` and
    ``get_health()``. Powers ``serve_combined --autoscale`` and the
    ``bench.py --scenario elastic-ab`` elastic arm, where a "lane" is a
    scheduler instance, not a remote process. Retired lanes are looked
    up by either the object or its lane NAME (the controller retires by
    name), stopped, and reported to ``on_retire`` so the host app can
    drop them from its own bookkeeping."""

    def __init__(self, factory, max_lanes: int = 0, on_retire=None):
        self._factory = factory
        self._max = int(max_lanes)
        self._on_retire = on_retire
        self._lock = threading.Lock()
        self._by_name: Dict[str, object] = {}
        self._next_idx = 0

    def spawn(self):
        with self._lock:
            if self._max and len(self._by_name) >= self._max:
                return None
            idx = self._next_idx
            self._next_idx += 1
        try:
            worker = self._factory(idx)
        except Exception:
            return None
        if worker is not None:
            with self._lock:
                self._by_name[str(getattr(worker, "node_id", worker))] = \
                    worker
        return worker

    def destroy(self, handle) -> None:
        self.retire(handle)

    def retire(self, handle) -> None:
        name = str(getattr(handle, "node_id", handle))
        with self._lock:
            worker = self._by_name.pop(name, None)
        if worker is None:
            worker = handle if not isinstance(handle, str) else None
        if worker is None:
            return
        stop = getattr(worker, "stop", None)
        if callable(stop):
            try:
                stop()
            except Exception:
                pass
        if self._on_retire is not None:
            try:
                self._on_retire(worker)
            except Exception:
                pass

    def capacity(self) -> Optional[int]:
        with self._lock:
            if not self._max:
                return None  # unbounded
            return max(0, self._max - len(self._by_name))


class FleetAutoscaler:
    """The gateway-side elastic-fleet controller.

    Two halves share one actuator ladder:

    - ``start()`` runs the closed loop (``--autoscale``): each tick
      observes per-lane pressure, publishes the mean, auto-clears stale
      ``spawn-wedged`` states, and actuates at most ONE decision —
      spawn (mean above ``autoscale_up_pressure``), retire (below
      ``autoscale_down_pressure``), or role flip (prefill:decode
      pressure ratio outside the hysteresis band) — subject to the
      min/max lane clamps, the actuation cooldown, and the blind-hold
      rule (no decision on zero samples; no retirement unless EVERY
      lane was observed — an unobservable lane must never read as
      idle). Suppressed decisions count as ``decisions_held``.
    - ``scale_up`` / ``scale_down`` / ``rebalance`` are the manual
      ``/admin/fleet`` actuations; they never touch the loop's
      thread-owned state, so an UNSTARTED controller serves them with
      identical semantics (probe gate, drain+migrate ladder, named
      degraded states, counters==spans).
    """

    def __init__(self, gateway, provider=None, config=None):
        self.gateway = gateway
        self.provider = provider
        self.config = config if config is not None else gateway.config
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Bounded actuation pool: a wedged remove_worker occupies one
        # slot past its timeout instead of hanging the caller. Created
        # on demand — the manual /admin/fleet surface outlives stop().
        self._exec: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._exec_lock = threading.Lock()
        # Loop-owned state (touched only from _run/_tick; registered as
        # thread-owned in tools/analyze/registry.py).
        self._last_action_ts = 0.0
        self._rebalance_armed = True

    # -- lifecycle ------------------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_event.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self._thread = None
        # A stopped controller still serves /admin/fleet with identical
        # semantics: re-arm the probe gate's wait and retire the
        # actuator pool (a later manual action re-creates it).
        self._stop_event.clear()
        with self._exec_lock:
            ex, self._exec = self._exec, None
        if ex is not None:
            ex.shutdown(wait=False)

    def _actuators(self) -> concurrent.futures.ThreadPoolExecutor:
        with self._exec_lock:
            if self._exec is None:
                self._exec = concurrent.futures.ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="fleet-actuator")
            return self._exec

    def _run(self) -> None:
        interval = max(0.05, float(self.config.autoscale_interval_s))
        while not self._stop_event.wait(interval):
            try:
                self._tick()
            except Exception:
                # The loop must survive any single tick's failure — a
                # controller crash must never take serving with it.
                pass

    # -- observation ----------------------------------------------------------

    def observe(self) -> Dict[str, Optional[float]]:
        """One pressure sample per lane (``None`` = unreachable or no
        load signal). Uses the dedicated probe connection on HTTP lanes
        so pool exhaustion by long streams never reads as pressure-0."""
        out: Dict[str, Optional[float]] = {}
        for lane, client in self.gateway.lane_clients().items():
            try:
                probe = getattr(client, "probe_health", None)
                health = probe(timeout_s=2.0) if callable(probe) \
                    else client.health()
                out[lane] = lane_pressure(health)
            except Exception:
                out[lane] = None
        return out

    def fleet_pressure(self, samples: Dict[str, Optional[float]]) -> float:
        vals = [v for v in samples.values() if v is not None]
        return sum(vals) / len(vals) if vals else 0.0

    # -- the closed loop ------------------------------------------------------

    def _tick(self) -> None:
        gw = self.gateway
        samples = self.observe()
        lanes = sorted(samples)
        mean = self.fleet_pressure(samples)
        if getattr(self.config, "autoscale_slo_feed", False):
            # SLO burn feed (observability plane, opt-in): take the MAX
            # of lane pressure and the worst objective's burn mapped to
            # [0, 1]. The feed only ever ADDS pressure — an idle fleet
            # burning budget (e.g. TTFT blown by compile stalls) scales
            # up, but a healthy burn can never mask lane saturation.
            try:
                mean = max(mean, gw.slo_pressure())
            except Exception:
                pass  # telemetry must never wedge the control loop
        gw.fleet_observe(mean)
        blind = sum(1 for v in samples.values() if v is None)

        # Blind-hold: a lane that cannot be observed (health blocked
        # behind a compile, a saturated accept loop, a stalled box) must
        # never read as IDLE. With no samples at all there is no basis
        # for any decision; scaling DOWN additionally requires every
        # lane observed — the unobservable lane may be the loaded one,
        # and retirement is the unsafe direction (scale-up on partial
        # data only adds capacity).
        if blind == len(samples):
            gw._fleet_count("decisions_held", reason="blind",
                            pressure=round(mean, 4))
            return

        # Recovery sweep: a spawn-wedged lane that later turned healthy
        # and joined the ring clears its own state (drain-wedged is an
        # operator signal — a kill -9 mid-drain stays latched until
        # /admin/fleet clear says it was seen).
        for lane, reason in list(gw.fleet_status()["degraded"].items()):
            if reason == DEGRADED_SPAWN_WEDGED and lane in samples:
                gw.fleet_clear_degraded(lane)

        if self._maybe_rebalance(samples):
            return

        n = len(lanes)
        up = mean > float(self.config.autoscale_up_pressure)
        down = mean < float(self.config.autoscale_down_pressure)
        if not up and not down:
            return
        max_lanes = int(self.config.autoscale_max_lanes)
        min_lanes = max(1, int(self.config.autoscale_min_lanes))
        if up and max_lanes and n >= max_lanes:
            gw._fleet_count("decisions_held", reason="max-lanes",
                            pressure=round(mean, 4))
            return
        if up and (self.provider is None
                   or self.provider.capacity() == 0):
            gw._fleet_count("decisions_held", reason="provider-exhausted",
                            pressure=round(mean, 4))
            return
        if down and n <= min_lanes:
            gw._fleet_count("decisions_held", reason="min-lanes",
                            pressure=round(mean, 4))
            return
        if down and blind:
            gw._fleet_count("decisions_held", reason="blind",
                            pressure=round(mean, 4))
            return
        now = time.monotonic()
        if now - self._last_action_ts \
                < float(self.config.autoscale_cooldown_s):
            gw._fleet_count("decisions_held", reason="cooldown",
                            pressure=round(mean, 4))
            return
        if up:
            res = self.scale_up()
        else:
            victim = self._pick_victim(samples)
            if victim is None:
                gw._fleet_count("decisions_held", reason="no-victim",
                                pressure=round(mean, 4))
                return
            res = self.scale_down(name=victim)
        if res.get("status") != "already-member":
            self._last_action_ts = time.monotonic()

    def _maybe_rebalance(self, samples: Dict[str, Optional[float]]) -> bool:
        """The role-rebalance arm: flip one lane prefill<->decode when
        the observed pressure ratio leaves the hysteresis band; re-arm
        only once it returns inside band/2. Never strands a role at
        zero lanes. Returns True when a flip was actuated."""
        band = float(self.config.autoscale_rebalance_band)
        if band <= 1.0 or not self.config.disagg:
            return False
        roles = self.gateway.worker_roles()
        pre = [v for l, v in samples.items()
               if v is not None and roles.get(l) == "prefill"]
        dec = [v for l, v in samples.items()
               if v is not None and roles.get(l) in ("decode", "both")]
        if not pre or not dec:
            return False
        eps = 1e-3
        ratio = (sum(pre) / len(pre) + eps) / (sum(dec) / len(dec) + eps)
        if not self._rebalance_armed:
            if 2.0 / band <= ratio <= band / 2.0:
                self._rebalance_armed = True
            return False
        now = time.monotonic()
        if now - self._last_action_ts \
                < float(self.config.autoscale_cooldown_s):
            return False
        target_role = None
        if ratio > band and sum(
                1 for l in samples if roles.get(l) in ("decode", "both")) > 1:
            # Prefill side starved: flip the least-pressured decode lane.
            target_role = "prefill"
            pool = [l for l in samples
                    if roles.get(l) in ("decode", "both")]
        elif ratio < 1.0 / band and sum(
                1 for l in samples if roles.get(l) == "prefill") > 1:
            target_role = "decode"
            pool = [l for l in samples if roles.get(l) == "prefill"]
        if target_role is None:
            return False
        victim = min(pool, key=lambda l: (samples.get(l) or 0.0, l))
        self._rebalance_armed = False
        res = self.rebalance(victim, target_role)
        if res.get("ok"):
            self._last_action_ts = time.monotonic()
        return True

    def _pick_victim(self, samples: Dict[str, Optional[float]]) \
            -> Optional[str]:
        """Scale-down victim: a reachable, non-degraded, non-ejected
        lane — lowest (ring weight, journaled streams, pressure), so
        the cheapest, emptiest lane drains first and the fewest streams
        ride the migration path. Under disagg, never the last lane of
        a role."""
        gw = self.gateway
        degraded = gw.fleet_status()["degraded"]
        streams: Dict[str, int] = {}
        for _rid, lane in gw.active_streams().items():
            streams[lane] = streams.get(lane, 0) + 1
        roles = gw.worker_roles()
        role_counts: Dict[str, int] = {}
        for lane in samples:
            role_counts[roles.get(lane, "both")] = \
                role_counts.get(roles.get(lane, "both"), 0) + 1
        candidates = []
        for lane, p in samples.items():
            if p is None or lane in degraded:
                continue
            if gw._probe_state.ejected(lane):
                continue
            role = roles.get(lane, "both")
            if self.config.disagg and role in ("prefill", "decode") \
                    and role_counts.get(role, 0) <= 1:
                continue
            candidates.append(
                (gw._ring.node_weight(lane), streams.get(lane, 0),
                 p, lane))
        if not candidates:
            return None
        return min(candidates)[3]

    # -- actuators (shared by the loop and /admin/fleet) ----------------------

    def scale_up(self, worker=None) -> dict:
        """Probe-then-register: acquire a lane (the given worker, or
        one from the provider), poll its ``/health`` until it reports
        healthy, and only then put it on the rings. A lane that never
        turns healthy within ``autoscale_spawn_timeout_s`` is handed
        back to the provider and latches the named ``spawn-wedged``
        degraded state — the fleet keeps serving on what it has."""
        gw = self.gateway
        cfg = self.config
        from_provider = worker is None
        if from_provider:
            worker = self.provider.spawn() if self.provider is not None \
                else None
            if worker is None:
                gw._fleet_count("scale_up_attempted", source="provider")
                gw._fleet_count("scale_up_failed",
                                reason="provider-exhausted")
                return {"ok": False, "status": "provider-exhausted"}
        if isinstance(worker, str):
            probe_client = HttpWorkerClient(
                worker, timeout_s=cfg.worker_timeout_s,
                default_port=cfg.default_worker_port, pool_size=2)
            name_hint = probe_client.url
            probe = lambda: probe_client.probe_health(timeout_s=2.0)
        else:
            name_hint = str(getattr(worker, "node_id", worker))
            probe = worker.get_health
        if name_hint in gw.lane_clients():
            return {"ok": True, "status": "already-member",
                    "worker": name_hint}
        gw._fleet_count("scale_up_attempted", worker=name_hint)
        deadline = time.monotonic() + float(cfg.autoscale_spawn_timeout_s)
        healthy = False
        while time.monotonic() < deadline:
            try:
                if bool(probe().get("healthy")):
                    healthy = True
                    break
            except Exception:
                pass
            if self._stop_event.wait(0.2):
                break
        if not healthy:
            gw.fleet_enter_degraded(name_hint, DEGRADED_SPAWN_WEDGED)
            gw._fleet_count("scale_up_failed", worker=name_hint,
                            reason=DEGRADED_SPAWN_WEDGED)
            if from_provider and self.provider is not None:
                try:
                    self.provider.destroy(worker)
                except Exception:
                    pass
            return {"ok": False, "status": DEGRADED_SPAWN_WEDGED,
                    "worker": name_hint}
        name = gw.add_worker(worker)
        gw.fleet_clear_degraded(name)
        gw._fleet_count("scale_up_completed", worker=name)
        return {"ok": True, "status": "registered", "worker": name}

    def scale_down(self, name: Optional[str] = None,
                   manual: bool = False) -> dict:
        """Retire one lane through the PR 11 ladder: bounded graceful
        drain, live stream migration, ring removal — zero tokens lost
        (replay resume is the ladder's own last rung). The whole
        actuation is bounded: a removal that exceeds the drain +
        migration budget latches ``drain-wedged`` and returns with the
        fleet still serving; a drain CALL that failed inside a removal
        that otherwise completed latches the same state as an operator
        signal (the kill -9 mid-drain shape) while membership still
        shrinks."""
        gw = self.gateway
        if name is None:
            name = self._pick_victim(
                {l: 0.0 for l in gw.lane_clients()})
            if name is None:
                return {"ok": False, "status": "no-victim"}
        if name not in gw.lane_clients():
            return {"ok": False, "status": "unknown-lane", "worker": name}
        gw._fleet_count("scale_down_attempted", worker=name,
                        manual=manual)
        before = gw.migration.get("drain_failures")
        budget = (float(self.config.drain_timeout_s)
                  + 2.0 * float(self.config.migrate_timeout_s) + 15.0)
        fut = self._actuators().submit(gw.remove_worker, name, True)
        try:
            fut.result(timeout=budget)
        except concurrent.futures.TimeoutError:
            gw.fleet_enter_degraded(name, DEGRADED_DRAIN_WEDGED)
            gw._fleet_count("scale_down_failed", worker=name,
                            reason="actuator-timeout")
            return {"ok": False, "status": DEGRADED_DRAIN_WEDGED,
                    "worker": name}
        except Exception as exc:
            gw._fleet_count("scale_down_failed", worker=name,
                            reason="remove-error")
            return {"ok": False, "status": "remove-failed",
                    "worker": name, "error": str(exc)[:200]}
        wedged = gw.migration.get("drain_failures") > before
        if wedged:
            gw.fleet_enter_degraded(name, DEGRADED_DRAIN_WEDGED)
        if self.provider is not None \
                and hasattr(self.provider, "retire"):
            try:
                self.provider.retire(name)
            except Exception:
                pass
        gw._fleet_count("scale_down_completed", worker=name,
                        wedged=wedged)
        return {"ok": True,
                "status": "removed-degraded" if wedged else "removed",
                "worker": name}

    def rebalance(self, name: str, role: str) -> dict:
        """Flip one lane's role through ``Gateway.set_worker_role`` —
        the /admin/role drain + migrate + set-role + undrain path, whose
        failure leg restores admissions and the old role on both sides."""
        gw = self.gateway
        gw._fleet_count("rebalance_attempted", worker=name, role=role)
        if name not in gw.lane_clients():
            gw._fleet_count("rebalance_failed", worker=name,
                            reason="unknown-lane")
            return {"ok": False, "status": "unknown-lane", "worker": name}
        try:
            res = gw.set_worker_role(name, role)
        except Exception as exc:
            gw._fleet_count("rebalance_failed", worker=name,
                            reason="flip-error")
            return {"ok": False, "status": "rebalance-failed",
                    "worker": name, "error": str(exc)[:200]}
        if res.get("ok"):
            gw._fleet_count("rebalance_completed", worker=name, role=role)
            return {"ok": True, "status": "rebalanced", "worker": name,
                    "role": role}
        gw._fleet_count("rebalance_failed", worker=name,
                        reason="flip-refused")
        return {"ok": False, "status": "rebalance-failed", "worker": name,
                "error": str(res.get("error", ""))[:200]}
