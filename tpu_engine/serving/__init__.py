"""tpu_engine.serving"""
