"""SLO burn-rate accounting over the existing latency histograms.

The reference has no latency objectives at all — its benchmark prints
means and walks away. The serving layer already *measures* everything an
objective needs: TTFT and inter-token latency feed per-lane
``LatencyHistogram``s (``tpu_engine_ttft/itl_seconds``) and every
request-level span feeds the per-op histograms in ``SpanRecorder``. This
module adds the *accounting*: declarative objectives
(``--slo-ttft-p99-ms`` / ``--slo-itl-p99-ms`` / ``--slo-completion-p99-ms``)
are evaluated against those histograms — no new measurement path, no new
per-request work — and a sliding window turns them into the SRE-standard
error-budget burn rate.

Math (documented in DESIGN.md "Observability plane"):

- An objective is (threshold_ms, target) — "``target`` of samples must
  finish under ``threshold_ms``". The error budget is ``1 - target``.
- ``violations`` = samples above the largest histogram bucket boundary
  ≤ the threshold (bucket quantization: the effective threshold is that
  boundary; with the default log-spaced buckets it is within ~2.5x and
  the /admin/slo payload reports the boundary actually used).
- Burn rate = (windowed violation fraction) / (error budget): 1.0 means
  the fleet is burning budget exactly at the sustainable rate; 2.0 means
  the budget exhausts in half the period; 0 = no violations.

Bounded state: one (ts, count, violations) tuple per objective per
status() call, pruned to the window — the tracker samples when scraped
(/admin/slo, /stats, the autoscaler feed), not on a timer of its own.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

# Objective key -> the named-histogram family it reads (TTFT / ITL are
# decode-lane measurements; completion reads the gateway's own
# request-level op histograms instead — see completion_hists()).
OBJECTIVE_SOURCES = {
    "ttft": "tpu_engine_ttft_seconds",
    "itl": "tpu_engine_itl_seconds",
    "completion": None,
}

# Request-level ops whose per-op histograms constitute "completion":
# full client-visible latency of a generate stream at gateway scope.
COMPLETION_OPS = ("generate", "generate_stream")


def violations_over(snapshot: dict, threshold_s: float) -> Tuple[int, float]:
    """(violations, effective_threshold_s) for one histogram snapshot:
    samples above the largest bucket boundary ≤ the threshold. Cumulative
    buckets make this one subtraction; the effective threshold reported
    is the boundary actually used (bucket quantization is explicit, not
    silent)."""
    le = snapshot["le"]
    idx = bisect.bisect_right(le, threshold_s) - 1
    if idx < 0:
        # Threshold below the first bucket: every sample counts against.
        return snapshot["count"], 0.0
    return (snapshot["count"] - snapshot["cumulative"][idx], le[idx])


class SloTracker:
    """Windowed error-budget burn over declarative latency objectives.

    Construction reads the ``slo_*`` gateway config fields; with no
    objective set the gateway never constructs one (the house
    defaults-off rule: no tracker, no /stats block, no metrics family).
    """

    def __init__(self, objectives_ms: Dict[str, float], target: float,
                 window_s: float):
        # name -> threshold in SECONDS (config speaks ms, hists seconds).
        self.objectives = {name: ms / 1e3
                           for name, ms in objectives_ms.items() if ms > 0}
        self.target = float(target)
        self.budget = max(1e-9, 1.0 - self.target)
        self.window_s = float(window_s)
        # name -> deque[(ts, count, violations)], pruned to window_s.
        self._samples: Dict[str, deque] = {
            name: deque() for name in self.objectives}
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, config) -> Optional["SloTracker"]:
        objectives = {
            "ttft": getattr(config, "slo_ttft_p99_ms", 0.0),
            "itl": getattr(config, "slo_itl_p99_ms", 0.0),
            "completion": getattr(config, "slo_completion_p99_ms", 0.0),
        }
        if not any(v > 0 for v in objectives.values()):
            return None
        return cls(objectives, config.slo_target, config.slo_window_s)

    def status(self, hists_by_objective: Dict[str, Iterable]) -> dict:
        """Evaluate every objective against the given histograms (any
        object with ``snapshot()``), record one window sample, and return
        the /admin/slo payload. Callers own histogram gathering — this
        module never imports the serving topology."""
        now = time.time()
        out: Dict[str, dict] = {}
        with self._lock:
            for name, thr in sorted(self.objectives.items()):
                count = violations = 0
                effective = 0.0
                for h in hists_by_objective.get(name) or ():
                    snap = h.snapshot()
                    v, eff = violations_over(snap, thr)
                    count += snap["count"]
                    violations += v
                    effective = eff or effective
                ring = self._samples[name]
                ring.append((now, count, violations))
                while ring and ring[0][0] < now - self.window_s:
                    ring.popleft()
                t0, c0, v0 = ring[0]
                d_count = count - c0
                d_viol = violations - v0
                frac = (d_viol / d_count) if d_count > 0 else 0.0
                good = (1.0 - violations / count) if count else None
                out[name] = {
                    "objective_ms": round(thr * 1e3, 3),
                    "effective_threshold_ms": round(effective * 1e3, 3),
                    "samples": count,
                    "violations": violations,
                    "good_fraction": (round(good, 6)
                                      if good is not None else None),
                    "window_s": round(min(self.window_s, now - t0), 1),
                    "window_samples": d_count,
                    "window_violations": d_viol,
                    "burn_rate": round(frac / self.budget, 4),
                }
        return {
            "target": self.target,
            "error_budget": round(self.budget, 6),
            "window_s": self.window_s,
            "objectives": out,
        }

    @staticmethod
    def pressure(status: dict) -> float:
        """Autoscaler feed: the worst objective's burn mapped into the
        [0, 1] pressure scale the fleet controller speaks. burn 2.0 (the
        classic page-now threshold) saturates to 1.0; burn 0 = no
        pressure — so the feed can only ADD pressure, never mask lane
        saturation (the controller takes max(lane, slo))."""
        worst = 0.0
        for obj in (status.get("objectives") or {}).values():
            if obj.get("window_samples"):
                worst = max(worst, obj.get("burn_rate", 0.0))
        return min(1.0, worst / 2.0)


def completion_hists(recorders: Iterable) -> List:
    """The 'completion' objective's histogram set: request-level
    generate-op histograms from span recorders (gateway scope — full
    client-visible latency including failover/handoff/migration time)."""
    out = []
    for rec in recorders:
        hists = rec.histograms()
        for op in COMPLETION_OPS:
            if op in hists:
                out.append(hists[op])
    return out
