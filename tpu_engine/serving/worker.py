"""WorkerNode: a serving lane — LRU result cache + dynamic batcher + engine.

Capability parity with the reference worker
(``/root/reference/src/worker_node.cpp``): ``handle_infer`` is cache-first
(``:50-83``), misses go through the dynamic batcher into batched execution,
and ``get_health`` exposes the exact JSON schema the reference documents
(``README.md:157-202``) and its tooling parses (``benchmark.py:148-178``,
``diagnostics.sh:39-56``).

TPU-native differences:
- the engine executes on a TPU chip (or mesh slice) through the
  shape-bucketed XLA executable cache instead of ONNX Runtime;
- per-request inference time is batch_duration / batch_size like the
  reference (``worker_node.cpp:123``), measured around the XLA dispatch;
- the result cache can be the native C++ LRU (byte-blob keys) when
  libtpucore.so is available.

A worker lane is addressable either over HTTP (reference deployment shape)
or in-process by the gateway (TPU-native shape: one process, lanes = chips).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import queue
import socket
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from tpu_engine.core.lru_cache import LRUCache
from tpu_engine.runtime.batch_processor import BatchProcessor
from tpu_engine.serving.http import sse_event
from tpu_engine.serving.overload import (
    AIMDLimit,
    BROWNOUT_BUDGET_FRAC,
    BROWNOUT_STAGES,
    BrownoutController,
    TIER_ADMIT_FRAC,
    TOP_TIER,
    parse_priority,
)
from tpu_engine.serving.resilience import AdmissionController
from tpu_engine.utils.config import WorkerConfig
from tpu_engine.utils.deadline import (
    Deadline,
    DeadlineExceeded,
    ShedError,
    clamp_timeout,
)
from tpu_engine.utils.sampling import clamp_top_k as _clamp_top_k
from tpu_engine.utils.sampling import validate_min_p as _validate_min_p
from tpu_engine.utils.sampling import expand_stopping_params
from tpu_engine.utils.tracing import SpanRecorder, TraceContext, TraceSink


@dataclass
class _BatchItem:
    request_id: str
    input_data: Sequence[float]
    shape: Optional[tuple] = None  # mixed-shape serving (BASELINE config 4)
    # The request's worker-root span context: queue_wait / batch_form /
    # device_compute stage spans parent here (utils.tracing).
    trace: Optional[TraceContext] = None


@dataclass
class _BatchResult:
    output_data: np.ndarray
    inference_time_us: int


class _RootSpan:
    """Mutable state of one worker-root span while its request runs: the
    span's context (stage children parent here), plus the cached flag and
    attrs the request path fills in before the scope records."""

    __slots__ = ("ctx", "request_id", "attrs", "cached")

    def __init__(self, ctx: TraceContext, request_id: str):
        self.ctx = ctx
        self.request_id = request_id
        self.attrs = {"outcome": "error"}
        self.cached = False


class _Inflight:
    """One in-flight computation shared by concurrent identical requests."""

    __slots__ = ("event", "frag", "time_us", "error")

    def __init__(self):
        self.event = threading.Event()
        self.frag: Optional[bytes] = None
        self.time_us = 0
        self.error: Optional[BaseException] = None


@dataclass
class _GenItem:
    request_id: str
    prompt: list
    max_new_tokens: int
    eos_id: int
    temperature: float
    seed: int
    top_p: float = 1.0
    top_k: int = 0
    repetition_penalty: float = 1.0
    stop_tokens: tuple = ()
    beam_width: int = 1
    length_penalty: float = 1.0
    min_p: float = 0.0
    trace: Optional[TraceContext] = None  # worker-root ctx (stage spans)


@dataclass
class _GenResult:
    tokens: list
    generate_time_us: int


@dataclass
class _ScoreItem:
    request_id: str
    prompt: list
    completion: list


def _load_model_path(model, model_path: Optional[str]):
    """Resolve the worker's model_path into a parameter pytree (or None for
    random init). HF checkpoint layouts (config.json / *.safetensors /
    pytorch_model.bin, or those files directly) go through the pretrained
    importers; other directories are treated as orbax checkpoints.
    `model` may be a registry name or an already-built ModelSpec (the
    HF-config-driven path) — a spec is passed through so the importer's
    architecture assertions run against it."""
    name = model if isinstance(model, str) else model.name
    spec = None if isinstance(model, str) else model
    if not model_path:
        return None
    if os.path.isfile(model_path):
        if model_path.endswith((".safetensors", ".bin", ".pt", ".pth")):
            from tpu_engine.models.import_weights import load_pretrained

            return load_pretrained(name, model_path, spec=spec)
        return None  # e.g. a reference-style .onnx path used only for naming
    if os.path.isdir(model_path):
        if any(os.path.exists(os.path.join(model_path, f))
               for f in ("config.json", "model.safetensors",
                         "pytorch_model.bin",
                         "model.safetensors.index.json")):
            from tpu_engine.models.import_weights import load_pretrained

            return load_pretrained(name, model_path, spec=spec)
        from tpu_engine.utils.checkpoint import load_params

        return load_params(model_path)
    return None


def _encode_output(arr) -> bytes:
    """Pre-encoded ``output_data`` fragment for the response/cache.

    Native %.6g writer when libtpucore is available (~3x json.dumps and
    GIL-free — the miss path pays this per request, and at b32 the Python
    encode alone was ~20 ms of GIL time per batch). Six significant
    digits is the serving noise floor: engines compute in bf16 (~3
    digits), and even float32 outputs keep ~1e-6 relative error. The
    fallback is the plain full-precision json.dumps — slower but never
    less accurate (decimal-place rounding would zero small magnitudes)."""
    from tpu_engine.core import native

    frag = native.json_encode_f32(arr)
    if frag is not None:
        return frag
    return json.dumps(np.asarray(arr, np.float64).tolist()).encode()


def _make_cache(capacity: int):
    # Values are the pre-encoded output_data JSON fragments (bytes) — raw
    # mode lets the native HTTP front read entries without unpickling.
    try:
        from tpu_engine.core import native

        if native.available():
            return native.NativeLRUCache(capacity, raw=True)
    except Exception:
        pass
    return LRUCache(capacity)


class WorkerNode:
    def __init__(self, config: Optional[WorkerConfig] = None, engine=None, **overrides):
        self.config = config or WorkerConfig.from_env(**overrides)
        self.node_id = self.config.node_id
        # Pre-escaped for the raw-splice response path: an operator-supplied
        # node_id containing quotes/backslashes must not corrupt the JSON.
        self._node_id_json = json.dumps(self.node_id).encode()
        if engine is None:
            from tpu_engine.runtime.engine import InferenceEngine

            if (self.config.model_path or "").endswith(".onnx"):
                # Arbitrary-ONNX serving (reference inference_engine.cpp:31-87):
                # the graph itself is staged to XLA — architecture AND weights
                # come from the file, no registry entry needed.
                from tpu_engine.models.onnx_graph import build_onnx_model

                if self.config.quantize is not None:
                    # ONNX initializers are flat named arrays, not the
                    # kernel dicts ops.quant rewrites — forwarding the flag
                    # would silently quantize nothing. Fail loudly instead.
                    raise RuntimeError(
                        "quantize is not supported for raw .onnx graphs "
                        "(import the checkpoint into a registry "
                        "architecture to serve quantized)")
                spec, params = build_onnx_model(self.config.model_path)
                engine = InferenceEngine(
                    spec,
                    params=params,
                    dtype=self.config.dtype,
                    batch_buckets=self.config.batch_buckets,
                    shape_buckets=self.config.shape_buckets,
                )
            else:
                # model_path (reference positional arg / $MODEL_PATH,
                # worker_node.cpp:154-168): real weights instead of random
                # init. Accepts an HF checkpoint dir / .safetensors / torch
                # .bin (via models.import_weights) or an orbax checkpoint
                # dir. An HF dir's config.json drives the architecture
                # (geometry AND shape-invariant fields like rope_theta) so
                # the engine spec matches the imported weights exactly.
                model = self.config.model
                if self.config.model_path and os.path.isdir(
                        self.config.model_path):
                    from tpu_engine.models.import_weights import hf_spec_kwargs
                    from tpu_engine.models.registry import (
                        create_model, _ensure_builtin_models_imported)

                    kwargs = hf_spec_kwargs(self.config.model_path)
                    if kwargs:
                        _ensure_builtin_models_imported()
                        model = create_model(self.config.model, **kwargs)
                params = _load_model_path(model, self.config.model_path)
                engine = InferenceEngine(
                    model,
                    params=params,
                    dtype=self.config.dtype,
                    batch_buckets=self.config.batch_buckets,
                    shape_buckets=self.config.shape_buckets,
                    quantize=self.config.quantize,
                )
        self.engine = engine
        # Tracing: one span ring per lane (request roots + stage children
        # + per-stage histograms). Created before the batchers so their
        # observer hook has a live recorder from the first batch on; the
        # engine reports its XLA compile events into the same ring so
        # first-request compile stalls are attributable in /trace/export.
        self.tracer = SpanRecorder(self.config.trace_capacity)
        try:
            self.engine.tracer = self.tracer
            self.engine.trace_node = self.node_id
        except AttributeError:
            pass  # test fakes with __slots__: engine tracing is optional
        self.cache = _make_cache(self.config.cache_capacity)
        self.batch_processor: BatchProcessor[_BatchItem, _BatchResult] = BatchProcessor(
            self.config.max_batch_size,
            self.config.batch_timeout_ms,
            self._process_batch,
            linger_ms=self.config.batch_linger_ms,
            name=f"{self.node_id}-batcher",
            # Split-phase pipelining needs engine.batch_submit/collect;
            # plain engines (tests inject batch_predict-only fakes) run the
            # reference-style lockstep loop.
            submit_callback=(self._submit_batch
                             if hasattr(self.engine, "batch_submit") else None),
            collect_callback=(self._collect_batch
                              if hasattr(self.engine, "batch_submit") else None),
            ready_callback=((lambda s: self.engine.handle_ready(s[0]))
                            if hasattr(self.engine, "handle_ready") else None),
            pipeline_depth=self.config.pipeline_depth,
            observer=self._batch_observer,
        )
        self.batch_processor.start()
        # Autoregressive generation lane (transformer models only): its own
        # batcher so decode loops never block one-shot /infer traffic.
        self.generator = None
        self._gen_processor: Optional[BatchProcessor[_GenItem, _GenResult]] = None
        self._continuous = self.config.gen_scheduler == "continuous"
        self._speculative = self.config.gen_scheduler == "speculative"
        # Unified stateless serving (DESIGN.md; the fold that retired
        # the dedicated batch lane): one-shot /infer and /score admit as
        # single-tick rows in the continuous scheduler — one slot pool,
        # one admission queue, one set of counters with decode streams.
        # Continuous-only: any other gen_scheduler keeps the batch lane.
        self._unified = (bool(getattr(self.config, "unified_stateless",
                                      True))
                         and self._continuous)
        if self.config.gen_continuous_spec_k > 0 and not self._continuous:
            # --spec-k is the continuous scheduler's knob; under any other
            # gen_scheduler the flag would build that lane's generator and
            # silently serve without speculation — same loud contract as
            # every other spec misconfiguration.
            raise RuntimeError(
                f"--spec-k requires gen_scheduler=continuous, got "
                f"{self.config.gen_scheduler!r} (batch-lane speculation "
                f"is gen_scheduler=speculative)")
        if self.config.gen_kv_host_blocks > 0 and (
                not self._continuous
                or self.config.gen_kv_block_size <= 0
                or not self.config.gen_prefix_sharing):
            # Loud, not the silent "this model can't generate" fallback:
            # an operator who asked for the host KV tier must never get a
            # lane that quietly recomputes every evicted prefix instead.
            raise RuntimeError(
                "--kv-host-blocks requires the continuous scheduler with "
                "the paged KV cache and prefix sharing on "
                "(--kv-block-size > 0, --prefix-sharing on)")
        if self.config.gen_kv_quantize and (
                not self._continuous
                or self.config.gen_kv_block_size <= 0):
            # Same loud contract: an operator who asked for the 2x KV
            # capacity multiplier must never get a lane that quietly
            # serves the full-precision (half-capacity) pool instead.
            raise RuntimeError(
                "--kv-quantize requires the continuous scheduler with "
                "the paged KV cache (--kv-block-size > 0)")
        if self.config.gen_kv_quantize not in ("", "int8"):
            raise RuntimeError(
                f"--kv-quantize must be 'int8', got "
                f"{self.config.gen_kv_quantize!r}")
        if self.config.gen_prefix_fetch and (
                not self._continuous
                or self.config.gen_kv_block_size <= 0
                or not self.config.gen_prefix_sharing):
            # Same loud contract: an operator who asked for the fleet
            # prefix tier must never get a lane that quietly ignores
            # every gateway hint and recomputes each shared prefix.
            raise RuntimeError(
                "--prefix-fetch requires the continuous scheduler with "
                "the paged KV cache and prefix sharing on "
                "(--kv-block-size > 0, --prefix-sharing on)")
        # Serving-state family fences (models.registry declares the
        # family; the worker refuses mismatched machinery LOUDLY — an
        # operator who asked for a kv_paged knob on a recurrent model
        # must never get a lane that quietly ignores it).
        model_family = getattr(self.engine.spec, "state_family", None)
        if model_family == "state_slab":
            if not self._continuous:
                raise RuntimeError(
                    f"model "
                    f"'{getattr(self.engine.spec, 'name', self.config.model)}'"
                    f" serves the state_slab family, which requires "
                    f"gen_scheduler=continuous (got "
                    f"{self.config.gen_scheduler!r}: the batch and "
                    f"speculative lanes serve only kv_paged models)")
            if (self.config.gen_kv_block_size > 0
                    or self.config.gen_kv_blocks > 0
                    or self.config.gen_kv_host_blocks > 0
                    or self.config.gen_kv_quantize):
                raise RuntimeError(
                    "state_slab-family models have no paged KV cache: "
                    "--kv-block-size/--kv-blocks/--kv-host-blocks/"
                    "--kv-quantize apply to the kv_paged family "
                    "(state capacity is --state-rows)")
            if self.config.gen_continuous_spec_k > 0:
                raise RuntimeError(
                    "--spec-k requires a kv_paged-family model: the "
                    "state_slab recurrence has no KV verify window")
        elif self.config.gen_state_rows > 0:
            raise RuntimeError(
                "--state-rows applies to state_slab-family models; "
                f"model "
                f"'{getattr(self.engine.spec, 'name', self.config.model)}'"
                f" serves the {model_family or 'kv_paged'} family")
        if model_family == "stateless":
            # Stateless-family fences: one-shot rows hold no
            # autoregressive state, so every generative-state knob is a
            # LOUD refusal — previously these were silently inert
            # (the generator was simply never built for config-less
            # models), which violated the misconfiguration contract.
            if self.config.gen_continuous_spec_k > 0:
                # Checked BEFORE the KV fence: an operator who asked for
                # speculation gets the speculative-lane diagnosis even
                # when KV knobs are also set (tests pin this wording).
                raise RuntimeError(
                    f"speculative lane misconfigured: --spec-k requires "
                    f"a generation-capable family; model "
                    f"'{getattr(self.engine.spec, 'name', self.config.model)}'"
                    f" serves the stateless family (one-shot rows have "
                    f"no decode loop to speculate)")
            if (self.config.gen_kv_block_size > 0
                    or self.config.gen_kv_blocks > 0
                    or self.config.gen_kv_host_blocks > 0
                    or self.config.gen_kv_quantize):
                raise RuntimeError(
                    "stateless-family models have no KV cache: "
                    "--kv-block-size/--kv-blocks/--kv-host-blocks/"
                    "--kv-quantize apply to the kv_paged family")
            if self.config.gen_mixed_step:
                raise RuntimeError(
                    "--mixed-step merges prefill and decode dispatches; "
                    "stateless-family models have neither (one-shot "
                    "rows already ride one grouped dispatch per tick)")
        # Tensor-parallel serving fences (the registry declares the
        # partition rule; the worker refuses misconfigurations LOUDLY —
        # an operator who asked for a sharded lane must never get a
        # silently single-device one, and an unshardable family must
        # never be heuristically mis-sharded).
        if int(self.config.tp) < 1:
            raise RuntimeError(f"--tp must be >= 1, got {self.config.tp}")
        if int(self.config.tp) > 1:
            # Unshardable family first: the pinned per-model refusal
            # (e.g. mamba2's conv tail/state slab) outranks the generic
            # knob-combination message.
            from tpu_engine.models.registry import tp_unshardable_reason

            reason = tp_unshardable_reason(self.engine.spec)
            if reason is not None:
                raise RuntimeError(
                    f"model "
                    f"'{getattr(self.engine.spec, 'name', self.config.model)}'"
                    f" cannot serve tensor-parallel (--tp "
                    f"{self.config.tp}): {reason}")
            if not self._continuous or self.config.gen_kv_block_size <= 0:
                raise RuntimeError(
                    "--tp requires the continuous scheduler with the "
                    "paged KV cache (--kv-block-size > 0): the sharded "
                    "pool layout is the paged pool")
        if self.config.role not in ("prefill", "decode", "both"):
            raise RuntimeError(
                f"--role must be prefill|decode|both, got "
                f"{self.config.role!r}")
        if self.config.role != "both" and (
                not self._continuous
                or (self.config.gen_kv_block_size <= 0
                    and model_family != "state_slab")):
            # A dedicated role without an exportable state family could
            # never export or adopt a chain — the lane would silently
            # serve colocated. Same loud contract as every other
            # misconfiguration. (state_slab rows export as
            # one-pseudo-block chains, so slab lanes qualify.)
            raise RuntimeError(
                "--role prefill|decode requires the continuous "
                "scheduler with the paged KV cache "
                "(--kv-block-size > 0)")
        if getattr(self.engine.spec, "config", None) is not None:
            try:
                if self._speculative:
                    # Draft-model speculation: batch-mode lane; the target
                    # verifies gen_spec_k draft tokens per windowed pass
                    # (runtime.speculative). Wire contract narrows to
                    # temperature sampling (handle_generate validates).
                    self.generator = self._build_speculative()
                    self._gen_processor = BatchProcessor(
                        self.config.gen_max_batch_size,
                        self.config.batch_timeout_ms,
                        self._process_gen_batch,
                        name=f"{self.node_id}-gen-batcher",
                        observer=self._batch_observer,
                    )
                    self._gen_processor.start()
                elif self._continuous:
                    # Iteration-level scheduling: the scheduler IS the
                    # batcher — HTTP handler threads submit directly and
                    # requests join the running decode batch between chunks.
                    from tpu_engine.runtime.scheduler import ContinuousGenerator

                    self.generator = ContinuousGenerator(
                        self.engine.spec, params=self.engine.params,
                        dtype=self.config.dtype,
                        n_slots=self.config.gen_max_batch_size,
                        step_chunk=self.config.gen_step_chunk,
                        prefix_cache_mb=self.config.gen_prefix_cache_mb,
                        prefill_chunk=self.config.gen_prefill_chunk,
                        kv_block_size=self.config.gen_kv_block_size,
                        kv_blocks=self.config.gen_kv_blocks,
                        kv_host_blocks=self.config.gen_kv_host_blocks,
                        kv_quantize=self.config.gen_kv_quantize,
                        prefix_sharing=self.config.gen_prefix_sharing,
                        mixed_step=self.config.gen_mixed_step,
                        mixed_token_budget=(
                            self.config.gen_mixed_token_budget),
                        state_rows=self.config.gen_state_rows,
                        # Unified stateless serving: one-shot /predict
                        # and /score requests admit as single-tick rows
                        # beside this lane's decode streams (one pool,
                        # one admission queue, one set of counters).
                        infer_engine=(self.engine if self._unified
                                      else None),
                        score_provider=(self._get_scorer
                                        if self._unified else None),
                        **self._continuous_spec_kwargs(),
                        # TP lanes build their own mesh over THIS
                        # lane's device slice (tp_device_offset keeps
                        # in-process TP lanes on disjoint chips); the
                        # engine's single-device pin is mutually
                        # exclusive.
                        tp=int(self.config.tp),
                        tp_devices=self._tp_devices(),
                        device=(None if int(self.config.tp) > 1
                                else getattr(engine, "_device", None)))
                    # Per-tick mixed_step spans land in the lane's ring.
                    self.generator.tracer = self.tracer
                    self.generator.trace_node = self.node_id
                    # Observability plane (all default off):
                    # --trace-stitch makes export snapshots carry the
                    # stream's trace context; --flight-recorder arms the
                    # per-tick ring behind /admin/timeline.
                    self.generator.trace_stitch = bool(
                        getattr(self.config, "trace_stitch", False))
                    flight = int(getattr(self.config,
                                         "flight_recorder", 0) or 0)
                    if flight > 0:
                        self.generator.configure_flight_recorder(
                            flight, getattr(self.config,
                                            "flight_dump_dir", None))
                    if self.config.gen_prefix_fetch:
                        # Fleet prefix tier: the scheduler calls this
                        # on its prefill thread for hinted misses; the
                        # worker owns transport, the per-lane in-flight
                        # cap, and the per-fetch timeout — the
                        # scheduler owns verification and the splice.
                        self.generator.prefix_fetch = \
                            self._fetch_prefix_peer
                else:
                    from tpu_engine.runtime.generator import Generator

                    self.generator = Generator(
                        self.engine.spec, params=self.engine.params,
                        dtype=self.config.dtype,
                        step_chunk=self.config.gen_step_chunk,
                        device=getattr(engine, "_device", None))
                    self._gen_processor = BatchProcessor(
                        self.config.gen_max_batch_size,
                        self.config.batch_timeout_ms,
                        self._process_gen_batch,
                        name=f"{self.node_id}-gen-batcher",
                        observer=self._batch_observer,
                    )
                    self._gen_processor.start()
            except ValueError as e:
                if self.config.gen_continuous_spec_k > 0:
                    # The operator explicitly asked for speculation: any
                    # construction failure (non-decoder draft model,
                    # draft max_seq too small for k, non-generating
                    # target) is a misconfiguration, not the quiet
                    # "this model can't generate" lane fallback.
                    raise RuntimeError(
                        f"speculative lane misconfigured: {e}") from e
                self.generator = None
        elif self._unified and model_family == "stateless":
            # Unified stateless serving: config-less models (mlp/resnet/
            # onnx graphs) get a continuous scheduler whose rows are ALL
            # one-shot — /predict misses join the same admission queue,
            # deadline governance, brownout tiers, and counters as every
            # generative lane in the fleet. n_slots mirrors the retired
            # batch lane's max batch so dispatch width is wire-identical.
            from tpu_engine.runtime.scheduler import ContinuousGenerator

            self.generator = ContinuousGenerator(
                self.engine.spec,
                params=getattr(self.engine, "params", None),
                dtype=self.config.dtype,
                n_slots=self.config.max_batch_size,
                prefix_cache_mb=0,
                infer_engine=self.engine,
                device=getattr(engine, "_device", None))
            self.generator.tracer = self.tracer
            self.generator.trace_node = self.node_id
            flight = int(getattr(self.config,
                                 "flight_recorder", 0) or 0)
            if flight > 0:
                self.generator.configure_flight_recorder(
                    flight, getattr(self.config, "flight_dump_dir",
                                    None))
        elif self.config.gen_continuous_spec_k > 0:
            # Config-less models skip generator construction entirely, so
            # the ValueError conversion above can never fire for them —
            # guard the skip path too, or --spec-k on a non-generating
            # model silently serves without a decode lane.
            raise RuntimeError(
                f"speculative lane misconfigured: model "
                f"'{getattr(self.engine.spec, 'name', self.config.model)}' "
                f"has no generation lane to speculate on")
        # Worker-level counters, distinct from the LRU's own accounting
        # (reference worker_node.cpp:141-142).
        self._total_requests = 0
        self._cache_hits = 0
        self._counter_lock = threading.Lock()
        # Fleet prefix tier transport state (--prefix-fetch): the
        # per-lane in-flight cap, a small peer-client cache for the
        # default HTTP transport, and an optional in-process transport
        # installed by combined-mode wiring (set_prefix_fetch_transport).
        self._prefix_fetch_sem = threading.BoundedSemaphore(
            max(1, int(getattr(self.config,
                               "gen_prefix_fetch_inflight", 2) or 1)))
        self._prefix_fetch_transport = None
        self._prefix_peers: dict = {}
        self._prefix_peers_lock = threading.Lock()
        # Fault injection (BASELINE config 5): the reference injects faults
        # by killing worker processes (README.md:322-349); in-process lanes
        # need an explicit hook. While set, every request raises — the
        # gateway's breaker sees it exactly like a dead worker.
        self._injected_fault: Optional[str] = None
        # Slow-lane fault (resilience scenarios): latency added to every
        # request while set — the lane is SLOW, not dead, which the
        # breaker alone cannot answer (hedging/deadlines do).
        self._injected_latency_s: float = 0.0
        self._fault_listeners: list = []
        # Resilience: bounded queue depth + drain (lame-duck) mode.
        # max_queue_depth=0 keeps admission unbounded (reference behavior).
        # Overload control (default off): the AIMD limiter replaces the
        # static cap with a latency-driven limit, and tier fractions
        # shed lowest-priority-first under depth pressure.
        # Start from the operator's static cap when one is configured —
        # the adaptive limit REPLACES max_queue_depth, so it must begin
        # where the operator's judgment left off, not at an arbitrary
        # midpoint.
        self._aimd = (AIMDLimit(max_limit=self.config.adaptive_depth_max,
                                start=self.config.max_queue_depth or None)
                      if self.config.adaptive_depth else None)
        self._tiered = bool(self.config.priority_admission)
        self._admission = AdmissionController(
            self.config.max_queue_depth, self.node_id,
            tier_fracs=TIER_ADMIT_FRAC if self._tiered else None,
            limiter=self._aimd)
        # Staged brownout (default off): a control loop reads saturation
        # signals every brownout_interval_s and walks the degradation
        # ladder (DESIGN.md "Overload control"); each transition drops an
        # `overload` marker span so escalations+restores == spans.
        self._brownout: Optional[BrownoutController] = None
        self._brownout_clamps = 0
        self._brownout_prev = {"starved": 0, "missed": 0}
        self._brownout_stop = threading.Event()
        self._brownout_thread: Optional[threading.Thread] = None
        if self.config.brownout:
            self._brownout = BrownoutController()
            self._brownout_thread = threading.Thread(
                target=self._brownout_loop,
                name=f"{self.node_id}-brownout", daemon=True)
            self._brownout_thread.start()
        # EWMA of recent miss-path per-request service time (µs), feeding
        # deadline-aware early rejection: a request whose remaining budget
        # cannot cover the typical miss is shed before it occupies a
        # batch row.
        self._service_ewma_us: Optional[float] = None
        # Bumped by reload_weights: in-flight /infer results computed
        # under an older generation must not enter the cleared cache. The
        # lock makes check+put atomic against bump+clear — a bare compare
        # would only narrow the race, not close it.
        self._weights_gen = 0
        self._reload_lock = threading.Lock()
        # In-flight coalescing: concurrent identical misses share ONE
        # execution. The reference deliberately lacks this — simultaneous
        # identical requests all enter the batch because the cache is only
        # written after the batch returns (worker_node.cpp:70-73;
        # SURVEY.md §3.2 flags it as a decision point). Followers wait on
        # the leader's event and reuse its encoded result.
        self._inflight: dict = {}
        self._inflight_lock = threading.Lock()
        # (total, hits) served on this lane's behalf outside this process's
        # Python path — the native HTTP front reports through here.
        self.external_counters = None
        # NOTE: self.tracer was created near the top of __init__ (the
        # engine, batchers, and generation scheduler all hold references
        # to it); a second assignment here would orphan their recorder —
        # their spans (xla_compile, mixed_step) would never export.

    # -- fault injection -------------------------------------------------------

    # Wire-facing beam cap: each distinct width compiles (and permanently
    # caches) its own while_loop executable and multiplies the KV cache by
    # the width — an unclamped client value is a compile/memory DoS.
    MAX_BEAM_WIDTH = 8

    def _validate_beam(self, beam_width, temperature, top_p, top_k,
                       rep_penalty, stop_tokens,
                       length_penalty: float = 1.0,
                       min_p: float = 0.0) -> None:
        if beam_width == 1:
            return  # non-beam paths never read length_penalty
        if not math.isfinite(length_penalty) or abs(length_penalty) > 10:
            # json.loads accepts NaN/Infinity; a non-finite penalty makes
            # every beam's normalized score NaN and silently returns [].
            raise ValueError(
                f"length_penalty must be finite in [-10, 10], got "
                f"{length_penalty}")
        if not 1 <= beam_width <= self.MAX_BEAM_WIDTH:
            raise ValueError(
                f"beam_width must be in [1, {self.MAX_BEAM_WIDTH}], got "
                f"{beam_width}")
        # Beam decode (batch lane's Generator only): deterministic,
        # incompatible with sampling controls by construction.
        if self._continuous or self._speculative:
            raise ValueError("beam_width > 1 needs gen_scheduler=batch")
        if (temperature > 0 or top_p < 1.0 or top_k > 0
                or rep_penalty != 1.0 or stop_tokens or min_p > 0):
            raise ValueError(
                "beam_width is deterministic: temperature/top_p/top_k/"
                "min_p/repetition_penalty/stop_tokens do not apply")


    def _tp_devices(self):
        """This lane's tensor-parallel device slice: ``tp`` devices
        starting at ``tp_device_offset`` (combined mode hands each
        in-process lane a disjoint slice; standalone workers keep
        offset 0 = the first tp devices). None when tp == 1. A slice
        running past the local devices is a loud startup error —
        silently wrapping would stack two lanes on one chip."""
        tp = int(self.config.tp)
        if tp <= 1:
            return None
        import jax

        off = int(self.config.tp_device_offset)
        devices = jax.devices()
        if off < 0 or off + tp > len(devices):
            raise RuntimeError(
                f"--tp {tp} at device offset {off} needs devices "
                f"[{off}, {off + tp}) but only {len(devices)} local "
                f"device(s) exist")
        return devices[off:off + tp]

    _AUTO_DRAFT = {"gpt2": "distilgpt2", "gpt2-small-test": "gpt2-small-test"}

    def _resolve_draft_spec(self):
        """Resolve the configured draft model (explicit gen_draft_model or
        the auto map) and optional checkpoint into (spec, params or None).
        Shared by the batch speculative lane and the continuous
        scheduler's --spec-draft model drafter. Raises RuntimeError on a
        misconfiguration so startup fails loudly."""
        from tpu_engine.models.registry import (
            create_model, _ensure_builtin_models_imported)

        draft_name = (self.config.gen_draft_model
                      or self._AUTO_DRAFT.get(self.engine.spec.name))
        if draft_name is None:
            raise RuntimeError(
                f"a draft model is required for "
                f"'{self.engine.spec.name}': set gen_draft_model "
                f"(--gen-draft-model)")
        _ensure_builtin_models_imported()
        # Same geometry sync the target path gets (worker init above): an
        # HF draft checkpoint dir's config.json overrides registry-default
        # shape-invariant fields (rope_theta etc.) so imported weights
        # compute with the right architecture, not defaults.
        draft_kwargs = {}
        if self.config.gen_draft_path and os.path.isdir(
                self.config.gen_draft_path):
            from tpu_engine.models.import_weights import hf_spec_kwargs

            draft_kwargs = hf_spec_kwargs(self.config.gen_draft_path) or {}
        try:
            draft_spec = create_model(draft_name, **draft_kwargs)
        except KeyError as exc:
            raise RuntimeError(f"speculative lane misconfigured: unknown "
                               f"draft model {exc}")
        draft_params = None
        if self.config.gen_draft_path:
            draft_params = _load_model_path(draft_spec,
                                            self.config.gen_draft_path)
        return draft_spec, draft_params

    def _continuous_spec_kwargs(self) -> dict:
        """Continuous-speculation kwargs for ContinuousGenerator
        (--spec-k / --spec-draft). Empty when off. Misconfiguration
        raises RuntimeError — the continuous branch's ValueError handler
        means "this model can't generate", and silently dropping the
        decode lane over a spec typo must not pass for that."""
        k = int(self.config.gen_continuous_spec_k)
        if k <= 0:
            return {}
        if self.config.gen_kv_block_size <= 0:
            raise RuntimeError(
                "--spec-k requires the paged KV cache (--kv-block-size)")
        max_seq = getattr(self.engine.spec.config, "max_seq", None)
        if max_seq is not None and k > max_seq - 2:
            # Pre-checked here because ContinuousGenerator's ValueError
            # would be read as "this model can't generate" and silently
            # drop the decode lane.
            raise RuntimeError(
                f"--spec-k {k} cannot fit a verify window in the "
                f"model's max_seq {max_seq}")
        if self.config.gen_spec_draft not in ("ngram", "model"):
            # Pre-checked so make_drafter's ValueError can't be read as
            # "this model can't generate" and silently drop the lane.
            raise RuntimeError(
                f"--spec-draft must be 'ngram' or 'model', got "
                f"{self.config.gen_spec_draft!r}")
        kw = {"spec_k": k, "spec_draft": self.config.gen_spec_draft}
        if self.config.gen_spec_draft == "model":
            draft_spec, draft_params = self._resolve_draft_spec()
            target_vocab = getattr(self.engine.spec.config, "vocab", None)
            if (target_vocab is not None
                    and draft_spec.config.vocab != target_vocab):
                raise RuntimeError(
                    f"speculative lane misconfigured: draft vocab "
                    f"{draft_spec.config.vocab} != target {target_vocab}")
            if draft_params is None:
                print(f"[{self.node_id}] WARNING: --spec-draft model "
                      f"'{draft_spec.name}' is randomly initialized (no "
                      f"gen_draft_path); expect ~zero acceptance — the "
                      f"ngram drafter is the better default", flush=True)
            kw["spec_draft_model"] = draft_spec
            kw["spec_draft_params"] = draft_params
        return kw

    def _build_speculative(self):
        """Construct the speculative-decoding lane (gen_scheduler=
        "speculative"): resolve the draft model (explicit config or the
        auto map), load optional draft weights, share the target's params
        with the engine.

        Error contract: the caller treats ValueError as "this model can't
        generate" (non-transformer targets fall back to no generation lane,
        same as the other schedulers), so ONLY the target-isn't-a-decoder
        case may raise ValueError here. Every speculative-specific
        misconfiguration (unresolvable draft, vocab mismatch, bad k) is
        re-raised as RuntimeError so startup fails loudly instead of
        silently serving without a generation lane."""
        from tpu_engine.models.transformer import TransformerConfig
        from tpu_engine.runtime.speculative import SpeculativeGenerator

        tgt_cfg = getattr(self.engine.spec, "config", None)
        if not isinstance(tgt_cfg, TransformerConfig) or not tgt_cfg.causal:
            raise ValueError(
                f"model '{self.engine.spec.name}' is not a decoder "
                "transformer; generation unsupported")
        draft_spec, draft_params = self._resolve_draft_spec()
        if draft_params is None:
            # A random-init draft accepts ~nothing: the lane degrades to
            # pure overhead (bench.py spec-ab's measured floor). Loud
            # warning, not an error — random drafts are the test fixture.
            print(f"[{self.node_id}] WARNING: speculative draft "
                  f"'{draft_spec.name}' is randomly initialized (no "
                  f"gen_draft_path); expect ~zero acceptance and worse "
                  f"throughput than gen_scheduler=batch", flush=True)
        try:
            return SpeculativeGenerator(
                self.engine.spec, draft_spec, params=self.engine.params,
                draft_params=draft_params, k=self.config.gen_spec_k,
                dtype=self.config.dtype,
                device=getattr(self.engine, "_device", None))
        except ValueError as exc:
            raise RuntimeError(f"speculative lane misconfigured: {exc}")

    def handle_score(self, request: dict) -> dict:
        """Teacher-forced scoring: per-token log P(completion | prompt) in
        one forward pass — the evals/perplexity API (lm-eval-harness
        loglikelihood shape). Wire: {request_id, prompt_tokens,
        completion_tokens} -> {request_id, logprobs, total_logprob,
        node_id}. Works under every gen_scheduler (a dedicated scorer
        shares the lane's params; first call compiles its bucket)."""
        if self._injected_fault is not None:
            raise RuntimeError(f"fault injected: {self._injected_fault}")
        self._check_model(request)
        from tpu_engine.models.transformer import TransformerConfig

        cfg = getattr(self.engine.spec, "config", None)
        if not isinstance(cfg, TransformerConfig) or not cfg.causal:
            # Teacher-forced next-token logprobs are a decoder-LM notion;
            # encoders (BERT dialect) reject with the scoring message, not
            # a confusing generation error from deeper in the stack.
            raise ValueError(
                f"model '{self.config.model}' does not support scoring")
        deadline = Deadline.from_request(request)
        tier = self._request_tier(request)
        with self._traced_request(request, "score") as span:
            with self._admitted(deadline, trace=(span.ctx,
                                                 span.request_id),
                                tier=tier):
                return self._score_admitted(request, deadline, span.ctx)

    def _score_admitted(self, request: dict,
                        deadline: Optional[Deadline],
                        tctx=None) -> dict:
        with self._counter_lock:
            self._total_requests += 1
        completion = [int(t) for t in request["completion_tokens"]]
        if not completion:
            raise ValueError("completion_tokens must be non-empty")
        item = _ScoreItem(request["request_id"],
                          [int(t) for t in request["prompt_tokens"]],
                          completion)
        scorer = self._get_scorer()
        total = max(len(item.prompt), 1) + len(completion)
        largest = scorer._prompt_buckets[-1]
        if total > largest:
            # Validate BEFORE the item joins a shared batch: one over-long
            # request must 400 alone, never poison its co-batched group.
            raise ValueError(
                f"prompt+completion length {total} exceeds the largest "
                f"sequence bucket {largest}")
        t0 = time.perf_counter()
        # Concurrent evals requests (the lm-eval-harness shape) batch into
        # one bucketed forward instead of N sequential batch-1 forwards.
        if self._score_unified():
            # Unified stateless serving: the score joins the continuous
            # scheduler as a single-tick row — same slot pool, deadlines,
            # brownout, and counters as the lane's decode streams. The
            # scheduler groups co-pending score rows into ONE bucketed
            # forward per tick (the retired score-batcher's semantics).
            sink = (TraceSink(self.tracer, self.node_id,
                              item.request_id, tctx)
                    if tctx is not None else None)
            fut = self.generator.submit_score(
                item.prompt, item.completion, deadline=deadline,
                sink=sink, tag=item.request_id)
            lps, _us = fut.result(
                timeout=(600.0 if deadline is None
                         else max(5.0, deadline.remaining_s() + 5.0)))
        else:
            lps = self._score_processor().process(item, deadline=deadline)
        return {
            "request_id": item.request_id,
            "logprobs": lps,
            "total_logprob": float(sum(lps)),
            "node_id": self.node_id,
            "score_time_us": int((time.perf_counter() - t0) * 1e6),
        }

    def _get_scorer(self):
        """The lane's scoring Generator: the batch scheduler's own
        Generator when it has one (shared executable caches), else a lazy
        dedicated instance sharing the lane's (possibly reloaded) params."""
        from tpu_engine.runtime.generator import Generator

        if isinstance(self.generator, Generator):
            return self.generator
        with self._counter_lock:
            scorer = getattr(self, "_scorer", None)
            if scorer is None:
                scorer = Generator(
                    self.engine.spec, params=self.engine.params,
                    dtype=self.config.dtype,
                    device=getattr(self.engine, "_device", None))
                self._scorer = scorer
        # Track hot reloads: params is a cheap reference swap.
        scorer.params = self.engine.params
        return scorer

    def _score_processor(self):
        proc = getattr(self, "_score_proc", None)
        if proc is None:
            with self._counter_lock:
                proc = getattr(self, "_score_proc", None)
                if proc is None:
                    proc = BatchProcessor(
                        self.config.max_batch_size,
                        self.config.batch_timeout_ms,
                        self._process_score_batch,
                        name=f"{self.node_id}-score-batcher",
                    )
                    proc.start()
                    self._score_proc = proc
        return proc

    def _process_score_batch(self, items):
        scorer = self._get_scorer()
        out = scorer.score([it.prompt for it in items],
                           [it.completion for it in items])
        return out

    def _check_model(self, request: dict) -> None:
        """A request addressed to a specific model must never be answered
        by a lane serving a different one (multi-model routing sends it to
        the right sub-ring; this guards misdirected/direct-port hits)."""
        want = request.get("model")
        have = getattr(self.engine.spec, "name", None)
        if want is not None and have is not None and str(want) != have:
            raise ValueError(
                f"this lane serves model '{have}', not '{want}'")

    def reload_weights(self, model_path: str) -> dict:
        """Hot weight reload: load a checkpoint for the SERVED architecture
        and swap it into every lane (one-shot engine + generation
        scheduler) without pausing serving. Swap semantics: a one-shot
        /infer batch completes atomically on whichever params it captured;
        a decode stream mid-flight picks up the new weights from its NEXT
        chunk (stop the lane first for a hard cut). Caches of old-weight
        results (/infer LRU, prefix cache) are invalidated, and late
        writes from in-flight old-weight work are fenced by a generation
        stamp. Architecture mismatches are rejected with the old weights
        still serving. (The reference's only weight-update path is
        restarting the worker process.)"""
        params = _load_model_path(self.engine.spec, model_path)
        if params is None:
            raise ValueError(f"no loadable weights at '{model_path}'")
        return self.apply_weights(params, source=model_path)

    def apply_weights(self, params, source: str = "<params>") -> dict:
        """The swap half of reload_weights — combined mode loads the
        checkpoint once and applies it per lane."""
        self.engine.set_params(params)  # validates + quantizes + places
        if self.generator is not None:
            if hasattr(self.generator, "set_params"):
                self.generator.set_params(self.engine.params)
            else:
                self.generator.params = self.engine.params
        with self._reload_lock:
            self._weights_gen += 1
            self.cache.clear()  # cached results came from old weights
        return {"ok": True, "node_id": self.node_id, "model_path": source}

    def inject_fault(self, reason: str = "injected") -> None:
        self._injected_fault = reason
        for listener in self._fault_listeners:
            listener(False)

    def inject_latency(self, seconds: float) -> None:
        """Slow-lane fault: every request sleeps this long before serving.
        The lane stays HEALTHY (no breaker trip from the fault itself) —
        exactly the failure mode deadlines and hedging exist for."""
        self._injected_latency_s = max(0.0, float(seconds))

    def heal(self) -> None:
        self._injected_fault = None
        self._injected_latency_s = 0.0
        for listener in self._fault_listeners:
            # A draining lane stays disabled at the native front even once
            # healed — drain outranks health for new admissions.
            listener(not self._admission.draining)

    def _maybe_slow(self) -> None:
        if self._injected_latency_s > 0:
            time.sleep(self._injected_latency_s)

    # -- overload control (priority tiers + staged brownout) -------------------

    def _request_tier(self, request: dict) -> Optional[int]:
        """The request's priority tier when an overload feature reads it
        (tiered admission or brownout clamping); None otherwise — with
        both off, the ``priority`` field is ignored entirely, additive
        and wire-compatible (MIGRATION.md). An unknown value with a
        feature ON is a 400, same contract as every validated field."""
        if not self._tiered and self._brownout is None:
            return None
        return parse_priority(request)

    def _brownout_clamp(self, max_new: int, tier: Optional[int]) -> int:
        """Stage-4 degradation: below-top-tier generate requests get
        their token budget clamped — the cheapest way to keep serving a
        low tier at all once every earlier stage is engaged. Top-tier
        work is never clamped."""
        bo = self._brownout
        if (bo is None or tier is None or tier >= TOP_TIER
                or bo.stage < BROWNOUT_STAGES.index("clamp")):
            return max_new
        clamp = max(1, int(self.config.brownout_clamp_tokens))
        if max_new > clamp:
            self._brownout_clamps += 1  # GIL-safe info counter
            return clamp
        return max_new

    def _brownout_signals(self) -> dict:
        """Collect the saturation components for one control-loop
        evaluation, each normalized so 1.0 = at the red line. All
        signals already exist — this only reads them."""
        comps = {}
        adm = self._admission
        limit = adm.effective_limit()
        # Queue pressure: admitted depth vs the concurrency limit, or —
        # unbounded lanes — vs twice the decode batch (the point where
        # queued work can no longer all be in a batch).
        nominal = limit or 2 * max(1, self.config.gen_max_batch_size)
        comps["queue_depth"] = adm.depth / nominal
        missed = adm.shed_deadline
        gen = self.generator
        st = None
        if gen is not None and hasattr(gen, "stats"):
            try:
                st = gen.stats()
            except Exception:
                st = None
        if st:
            # Decode-loop tick age vs the stall threshold (default red
            # line 2 s when none is configured): a loop spending whole
            # seconds inside one dispatch is saturated long before it is
            # wedged.
            age = st.get("last_tick_age_s")
            stall = float(self.config.scheduler_stall_s or 0.0) or 2.0
            if age is not None:
                comps["tick_age"] = age / stall
            kv = st.get("kv_pool") or {}
            if kv:
                # Pool starvation events and deferred admissions: rows
                # already competing for blocks.
                comps["pool_pending"] = (kv.get("pending_admissions", 0)
                                         / max(1, self.n_gen_slots()))
                starved = st.get("pool_starved", 0)
                if starved > self._brownout_prev["starved"]:
                    comps["pool_starved"] = 1.0
                self._brownout_prev["starved"] = starved
            missed += st.get("deadline_cancelled", 0)
        # Deadline misses since the last evaluation: work is already
        # arriving dead — the clearest "past the red line" signal.
        if missed > self._brownout_prev["missed"]:
            comps["deadline_miss"] = 1.0
        self._brownout_prev["missed"] = missed
        return comps

    def n_gen_slots(self) -> int:
        return max(1, int(self.config.gen_max_batch_size))

    def _apply_brownout(self, action: str, comps: dict) -> None:
        """Apply the controller's current stage to the lane and drop the
        matching ``overload`` marker span (one per transition — the
        escalations+restores counters and these spans must agree;
        fault_injection --overload asserts it)."""
        stage = self._brownout.stage
        gen = self.generator
        if gen is not None and hasattr(gen, "set_brownout"):
            gen.set_brownout(
                budget_frac=BROWNOUT_BUDGET_FRAC if stage >= 1 else 1.0,
                suspend_spec=stage >= 2,
                defer_swap_in=stage >= 3)
        ctx = TraceContext.root(f"brownout:{self.node_id}").child()
        binding = max(comps, key=comps.get) if comps else ""
        self.tracer.record(
            "brownout", "overload", self.node_id, 0,
            trace_id=ctx.trace_id, span_id=ctx.span_id,
            start_ts=time.time(),
            attrs={"action": action, "stage": stage,
                   "stage_name": BROWNOUT_STAGES[stage],
                   "binding_signal": binding})

    def _brownout_loop(self) -> None:
        """The control loop: read signals, walk the ladder, apply. Stage
        changes are the only side effects; a failed evaluation skips the
        sample (the loop must degrade the LANE, never kill it)."""
        interval = max(0.05, float(self.config.brownout_interval_s))
        while not self._brownout_stop.wait(interval):
            try:
                comps = self._brownout_signals()
                action = self._brownout.evaluate(comps)
                if action is not None:
                    self._apply_brownout(action, comps)
            except Exception:
                continue  # a torn stats read is a skipped sample

    @contextlib.contextmanager
    def _traced_request(self, request: dict, op: str):
        """Worker-root span scope shared by the blocking request paths
        (/infer, /generate, /score): parse the caller's traceparent (or
        derive a root from request_id), yield a `_RootSpan` whose ``ctx``
        parents every stage child, and record the root — wall time,
        outcome (ok / shed kind / error), plus whatever attrs the body
        added — however the body exits."""
        parent = TraceContext.from_request(request)
        request_id = str(request.get("request_id", ""))
        ctx = (parent.child() if parent is not None
               else TraceContext.root(request_id))
        span = _RootSpan(ctx, request_id)
        t0 = time.perf_counter()
        start = time.time()
        try:
            yield span
            span.attrs["outcome"] = "ok"
        except ShedError as exc:
            span.attrs["outcome"] = exc.kind
            raise
        finally:
            self.tracer.record(
                request_id, op, self.node_id,
                (time.perf_counter() - t0) * 1e6,
                cached=span.cached, trace_id=ctx.trace_id,
                span_id=ctx.span_id,
                parent_id=parent.span_id if parent is not None else None,
                start_ts=start, attrs=span.attrs)

    @contextlib.contextmanager
    def _admitted(self, deadline, trace=None, tier=None):
        """Admission scope shared by every blocking request path: admit
        (drain/depth/tier/expired-deadline can shed -> wire 503), apply
        the slow-lane fault, and ALWAYS release. The streaming path
        manages release by hand — its in-flight window is the iterator's
        life, not this frame's.

        ``tier``: the request's priority tier for tiered admission (None
        = untiered, the pre-overload-control behavior). A request that
        completes normally feeds its wall time to the AIMD limiter —
        latency observed WITH queueing included, which is exactly the
        congestion signal the limit adapts to.

        ``trace``: optional (TraceContext, request_id) — records an
        ``admission`` stage span (child of the worker root) whose duration
        covers the admit decision AND any injected slow-lane latency, so a
        slowed lane's traces show WHERE the time went. A shed records the
        span with the refusal kind before re-raising."""
        t0 = time.perf_counter()
        start = time.time()

        def _span(outcome):
            if trace is None:
                return
            ctx, request_id = trace
            child = ctx.child()
            self.tracer.record(
                request_id, "admission", self.node_id,
                (time.perf_counter() - t0) * 1e6,
                trace_id=child.trace_id, span_id=child.span_id,
                parent_id=ctx.span_id, start_ts=start,
                attrs={"outcome": outcome})

        try:
            self._admission.admit(deadline, tier=tier)
        except ShedError as exc:
            exc.stage = exc.stage or "worker_admission"
            _span(exc.kind)
            raise
        ok = False
        try:
            self._maybe_slow()
            _span("admitted")
            yield
            ok = True
        finally:
            self._admission.release()
            if ok and self._aimd is not None:
                self._aimd.observe(time.perf_counter() - t0)

    # -- drain (lame-duck) -----------------------------------------------------

    def drain(self) -> str:
        """Refuse new admissions (503 + Retry-After) while in-flight work
        completes — the lame-duck half of graceful removal. The gateway's
        ``remove_worker(drain=True)`` and ``/admin/drain`` drive this.
        Fault listeners fire too: the native C++ front must stop answering
        a draining lane's cache hits (its hit path never enters Python, so
        the admission check alone cannot reach it). Idempotent: a second
        drain answers the named ``already-draining`` status instead of
        re-running the side effects."""
        status = self._admission.drain()
        if status == "already-draining":
            return status
        gen = self.generator
        if gen is not None and hasattr(gen, "set_draining"):
            gen.set_draining(True)
        for listener in self._fault_listeners:
            listener(False)
        return status

    def undrain(self) -> str:
        """Inverse of :meth:`drain`; ``not-draining`` names the no-op
        (undrain of a lane that never drained — idempotent, never
        raises)."""
        status = self._admission.undrain()
        if status == "not-draining":
            return status
        gen = self.generator
        if gen is not None and hasattr(gen, "set_draining"):
            gen.set_draining(False)
        if self._injected_fault is None:  # don't resurrect a faulted lane
            for listener in self._fault_listeners:
                listener(True)
        return status

    @property
    def draining(self) -> bool:
        return self._admission.draining

    # -- live stream migration (DESIGN.md "Live stream migration") -------------

    def handle_migrate_export(self, request: dict) -> dict:
        """/admin/migrate: export ONE live stream's row — tokens
        emitted, sampling state, remaining budget, and its KV block
        chain — so the gateway can adopt it on another lane with zero
        re-prefilled tokens. The local stream ends with a retryable
        ``migrated`` terminal event. Refusals (unknown stream, mid-
        prefill row, non-paged lane) come back ``{"ok": False,
        "reason"}`` — never an error: the caller's fallback is the
        replay resume, which needs nothing from this lane."""
        rid = request.get("request_id")
        if not rid:
            raise ValueError("request_id is required")
        gen = self.generator
        if gen is None or not hasattr(gen, "export_row"):
            return {"ok": False, "node_id": self.node_id,
                    "reason": "this lane has no continuous decode "
                              "scheduler to export from"}
        timeout_s = float(request.get("timeout_s", 10.0))
        out = gen.export_row(str(rid), timeout_s=timeout_s,
                             wait_prefill=bool(
                                 request.get("wait_prefill", False)),
                             cancel=bool(request.get("cancel", False)))
        out["node_id"] = self.node_id
        return out

    # -- fleet prefix tier (DESIGN.md "Fleet-wide prefix tier") ----------------

    def handle_export_prefix(self, request: dict) -> dict:
        """/admin/export_prefix: serve a peer lane's prefix fetch — the
        longest radix chain matching the requested token prefix,
        serialized under one pool-lock pass (device-resident and
        host-demoted blocks alike; NO stream state — this is a cache
        read, not a migration). Refusals (draining lane, no scheduler,
        no matching chain) come back ``{"ok": False, "node_id",
        "reason"}`` and never raise: the fetching peer's fallback is
        local prefill, which needs nothing from this lane. The drain
        refusal names this node so a stale directory entry is
        attributable at the fetcher."""
        gen = self.generator
        if gen is None or not hasattr(gen, "export_prefix"):
            return {"ok": False, "node_id": self.node_id,
                    "reason": "this lane has no continuous decode "
                              "scheduler to export from"}
        if self.draining:
            return {"ok": False, "node_id": self.node_id,
                    "reason": f"lane {self.node_id} is draining"}
        tokens = request.get("tokens")
        if not isinstance(tokens, list) or not tokens:
            return {"ok": False, "node_id": self.node_id,
                    "reason": "request carries no token prefix"}
        max_blocks = request.get("max_blocks")
        out = gen.export_prefix(
            tokens, max_blocks=(int(max_blocks)
                                if max_blocks is not None else None))
        out["node_id"] = self.node_id
        return out

    def set_prefix_fetch_transport(self, fn) -> None:
        """Install an in-process peer transport (combined mode): a
        callable ``(hint, payload) -> dict`` replacing the default
        HTTP POST to the hint's address — in-process lanes have no
        URL to dial."""
        self._prefix_fetch_transport = fn

    def _fetch_prefix_peer(self, hint: dict, tokens,
                           max_blocks: int) -> Optional[dict]:
        """The fetch callable installed on the scheduler
        (--prefix-fetch): pull the hinted peer's chain, classifying
        every transport outcome into the fallback-ladder rung the
        scheduler counts (``peer_unreachable`` / ``peer_refused`` /
        ``timeout`` / ``inflight_capped``). Runs on the scheduler's
        prefill thread; the semaphore acquire is non-blocking so a
        thundering herd on one hot prefix degrades to local prefill,
        never a convoy. Returns None for a self-hint (a retry landed
        the request on the owner itself — nothing to fetch)."""
        if hint.get("lane") == self.node_id:
            return None
        if not self._prefix_fetch_sem.acquire(blocking=False):
            return {"ok": False, "rung": "inflight_capped"}
        try:
            payload = {"tokens": [int(t) for t in tokens],
                       "max_blocks": int(max_blocks)}
            timeout_s = max(0.1, float(getattr(
                self.config, "gen_prefix_fetch_timeout_s", 5.0)))
            if self._prefix_fetch_transport is not None:
                try:
                    out = self._prefix_fetch_transport(hint, payload)
                except Exception:
                    return {"ok": False, "rung": "peer_unreachable"}
            else:
                addr = hint.get("addr")
                if not addr:
                    return {"ok": False, "rung": "peer_unreachable",
                            "reason": "hint carries no peer address"}
                try:
                    out = self._prefix_peer_client(addr).export_prefix(
                        payload, timeout_s=timeout_s)
                except (socket.timeout, TimeoutError):
                    return {"ok": False, "rung": "timeout"}
                except Exception as exc:
                    if "timed out" in str(exc).lower():
                        return {"ok": False, "rung": "timeout"}
                    return {"ok": False, "rung": "peer_unreachable"}
            if not isinstance(out, dict) or not out.get("ok"):
                return {"ok": False, "rung": "peer_refused",
                        "reason": (out or {}).get("reason")
                        if isinstance(out, dict) else "malformed reply"}
            return {"ok": True, "chain": out.get("chain"),
                    "blocks": out.get("blocks")}
        finally:
            self._prefix_fetch_sem.release()

    def _prefix_peer_client(self, addr: str):
        """One cached HTTP client per peer address (the default fetch
        transport). Bounded: directory capacity bounds distinct hint
        addresses far below any worrying count, but cap anyway."""
        from tpu_engine.serving.clients import HttpWorkerClient

        with self._prefix_peers_lock:
            client = self._prefix_peers.get(addr)
            if client is None:
                if len(self._prefix_peers) >= 64:
                    self._prefix_peers.clear()
                client = HttpWorkerClient(
                    addr, timeout_s=max(0.1, float(getattr(
                        self.config, "gen_prefix_fetch_timeout_s", 5.0))),
                    pool_size=max(1, int(getattr(
                        self.config, "gen_prefix_fetch_inflight", 2) or 1)))
                self._prefix_peers[addr] = client
            return client

    def handle_timeline(self, request: Optional[dict] = None) -> dict:
        """/admin/timeline: the continuous scheduler's flight-recorder
        ring (per-tick records, newest last) plus dump bookkeeping.
        GET reads; POST {"dump": reason} forces a postmortem artifact.
        With the recorder unconfigured (the default) the payload says so
        and carries no timeline — the endpoint itself is additive."""
        gen = self.generator
        if gen is None or not hasattr(gen, "flight_timeline"):
            return {"node_id": self.node_id, "enabled": False,
                    "reason": "this lane has no continuous scheduler"}
        if request and request.get("dump"):
            dump = gen.flight_dump(str(request["dump"]))
            return {"node_id": self.node_id,
                    "enabled": dump is not None, "dumped": dump}
        n = int(request.get("n", 0)) if request else 0
        out = gen.flight_timeline(n or None)
        out["node_id"] = self.node_id
        return out

    def flight_dump(self, reason: str) -> Optional[dict]:
        """Force a flight-recorder dump (gateway degraded-fleet entry
        trigger). None when the lane has no armed recorder."""
        gen = self.generator
        if gen is None or not hasattr(gen, "flight_dump"):
            return None
        return gen.flight_dump(reason)

    def handle_profile(self, request: Optional[dict] = None) -> dict:
        """/admin/profile (worker): jax.profiler capture bounded in
        scheduler ticks. Requires --profile-dir. POST {"ticks": N}
        starts a capture the decode loop stops after N ticks;
        {"action": "stop"} stops early; {"action": "status"} / GET
        reports the countdown. Lanes without a continuous scheduler
        fall back to unbounded start/stop."""
        profile_dir = getattr(self.config, "profile_dir", None)
        request = request or {}
        action = request.get("action")
        gen = self.generator
        ticked = gen is not None and hasattr(gen, "start_profile")
        if action == "status":
            out = {"node_id": self.node_id, "profile_dir": profile_dir}
            if ticked:
                out.update(gen.profile_status())
            return out
        if action == "stop":
            from tpu_engine.utils import tracing

            res = gen.stop_profile() if ticked else tracing.profiler_stop()
            return {"node_id": self.node_id, **res}
        if not profile_dir:
            return {"node_id": self.node_id,
                    "error": "profiling not configured "
                             "(start the worker with --profile-dir)"}
        log_dir = request.get("log_dir") or profile_dir
        ticks = int(request.get("ticks", 0) or 0)
        if ticks > 0 and ticked:
            res = gen.start_profile(log_dir, ticks)
        else:
            from tpu_engine.utils import tracing

            res = tracing.profiler_start(log_dir)
        return {"node_id": self.node_id, **res}

    def set_role(self, role: str) -> dict:
        """/admin/role: flip this lane's serving role at runtime
        (fleet rebalancing under diurnal load — the gateway rides
        /admin/drain + stream migration around the flip). Role is
        advisory routing metadata: the lane keeps serving whatever it
        receives, so the flip itself is safe mid-traffic."""
        role = str(role)
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"role must be prefill|decode|both, "
                             f"got {role!r}")
        if role != "both" and (
                not self._continuous
                or (self.config.gen_kv_block_size <= 0
                    and getattr(self.engine.spec, "state_family", None)
                    != "state_slab")):
            raise ValueError(
                "a dedicated role requires the continuous scheduler "
                "with the paged KV cache (--kv-block-size > 0)")
        self.config.role = role
        return {"ok": True, "node_id": self.node_id, "role": role}

    @property
    def role(self) -> str:
        return self.config.role

    def on_fault_change(self, listener) -> None:
        """Register listener(healthy: bool) — the native HTTP front uses
        this to stop serving a faulted lane's cache hits in C++."""
        self._fault_listeners.append(listener)

    # -- request path ---------------------------------------------------------

    @staticmethod
    def _cache_key(input_data, shape=None) -> bytes:
        blob = np.asarray(input_data, dtype=np.float32).tobytes()
        if shape is not None:
            blob = np.asarray(shape, np.int64).tobytes() + b"|" + blob
        return blob

    def _infer_core(self, request: dict) -> Tuple[str, bytes, bool, int]:
        """Shared /infer flow → (request_id, pre-encoded JSON fragment of
        output_data, cached?, inference_time_us).

        The fragment is cached alongside the array: serializing ~1000
        floats costs ~670 µs in json.dumps but 1 µs to splice pre-encoded —
        on a ~99% hit-rate workload (the reference's own benchmark) that
        serialization dominated the whole request path.

        Tracing: the worker-side root span (op ``infer``) covers the full
        worker wall time — admission through response fragment ready —
        with per-stage children (admission, cache_lookup, queue_wait,
        batch_form, device_compute, serialize). Its parent is the
        caller's ``traceparent`` span when supplied; otherwise the root
        derives its trace_id from request_id, so gateway and worker
        correlate with zero wire change."""
        if self._injected_fault is not None:
            raise RuntimeError(f"fault injected: {self._injected_fault}")
        self._check_model(request)
        deadline = Deadline.from_request(request)
        tier = self._request_tier(request)
        with self._traced_request(request, "infer") as span:
            # Resilience: admission BEFORE the request counts — a shed
            # request never skews the reference-exact /health counters,
            # only its own (additive) admission block. Expired/overloaded/
            # draining raise here and surface as 503 + Retry-After.
            with self._admitted(deadline, trace=(span.ctx,
                                                 span.request_id),
                                tier=tier):
                with self._counter_lock:
                    self._total_requests += 1
                out = self._infer_admitted(request, deadline, span.ctx)
                span.cached = out[2]
                span.attrs["inference_time_us"] = out[3]
                return out

    def _infer_admitted(self, request: dict, deadline: Optional[Deadline],
                        tctx: TraceContext) -> Tuple[str, bytes, bool, int]:
        request_id = request["request_id"]
        input_data = request["input_data"]
        shape = request.get("shape")
        if shape is not None:
            shape = tuple(int(d) for d in shape)

        key = self._cache_key(input_data, shape)
        cl0 = time.perf_counter()
        cl_start = time.time()
        frag = self.cache.get(key)
        child = tctx.child()
        self.tracer.record(
            request_id, "cache_lookup", self.node_id,
            (time.perf_counter() - cl0) * 1e6,
            trace_id=child.trace_id, span_id=child.span_id,
            parent_id=tctx.span_id, start_ts=cl_start,
            attrs={"hit": frag is not None})
        if frag is not None:
            with self._counter_lock:
                self._cache_hits += 1
            # Reference reports a fixed fake latency on hits (:65).
            return request_id, frag, True, self.config.fake_cached_latency_us

        while True:
            # Miss path: deadline-aware early rejection against the
            # measured service-time EWMA — a doomed request sheds here for
            # the cost of a 503 instead of occupying a batch row it cannot
            # use. (Re-checked per coalescing round: this request's OWN
            # budget governs.)
            est = self._service_ewma_us
            self._admission.check_deadline(
                deadline, None if est is None else est / 1e6)

            with self._inflight_lock:
                entry = self._inflight.get(key)
                leader = entry is None
                if leader:
                    entry = _Inflight()
                    self._inflight[key] = entry
            if leader:
                break
            w0 = time.perf_counter()
            w_start = time.time()
            if not entry.event.wait(
                    timeout=clamp_timeout(deadline, 120.0)):
                if deadline is not None and deadline.expired():
                    raise DeadlineExceeded(
                        "deadline expired waiting on coalesced result")
                raise RuntimeError("coalesced request timed out")
            if entry.error is not None:
                if isinstance(entry.error, DeadlineExceeded):
                    # The LEADER's budget expired — a per-request fact,
                    # not a property of the input. This follower's budget
                    # may be fine: retire the dead entry (the leader's own
                    # pop may not have run yet; leaving it would make this
                    # loop spin on it) and recompute — next round it
                    # either joins a live leader or leads itself.
                    with self._inflight_lock:
                        if self._inflight.get(key) is entry:
                            self._inflight.pop(key)
                    continue
                # Re-raise the leader's exception unchanged so client-input
                # error types (KeyError/TypeError/ValueError) keep their
                # no-breaker-penalty classification in LocalWorkerClient —
                # a coalesced bad input must not count as a lane failure.
                raise entry.error
            child = tctx.child()
            self.tracer.record(
                request_id, "coalesced_wait", self.node_id,
                (time.perf_counter() - w0) * 1e6,
                trace_id=child.trace_id, span_id=child.span_id,
                parent_id=tctx.span_id, start_ts=w_start,
                attrs={"leader_time_us": entry.time_us})
            return request_id, entry.frag, False, entry.time_us

        try:
            gen0 = self._weights_gen  # stamp BEFORE the compute
            result = self._dispatch_infer(
                _BatchItem(request_id, input_data, shape, trace=tctx),
                deadline)
            s0 = time.perf_counter()
            s_start = time.time()
            frag = _encode_output(result.output_data)
            child = tctx.child()
            self.tracer.record(
                request_id, "serialize", self.node_id,
                (time.perf_counter() - s0) * 1e6,
                trace_id=child.trace_id, span_id=child.span_id,
                parent_id=tctx.span_id, start_ts=s_start)
            # A hot reload between compute and put would otherwise re-seed
            # the freshly cleared cache with an old-weight result forever;
            # check+put must be atomic against apply_weights' bump+clear.
            with self._reload_lock:
                if gen0 == self._weights_gen:
                    self.cache.put(key, frag)
            entry.frag = frag
            entry.time_us = result.inference_time_us
            # EWMA (0.2 step) of the miss-path service time — feeds the
            # early-rejection estimate above.
            t = float(result.inference_time_us)
            self._service_ewma_us = (t if self._service_ewma_us is None
                                     else 0.8 * self._service_ewma_us + 0.2 * t)
        except BaseException as exc:
            entry.error = exc
            raise
        finally:
            entry.event.set()
            with self._inflight_lock:
                self._inflight.pop(key, None)
        return request_id, frag, False, result.inference_time_us

    def handle_infer(self, request: dict) -> dict:
        """Serve one /infer payload; wire schema identical to the reference
        (``worker_node.cpp:50-83``). Additive field: optional "shape"
        [h, w, c] for mixed-shape models (engine shape buckets)."""
        request_id, frag, cached, time_us = self._infer_core(request)
        return {
            "request_id": request_id,
            "output_data": json.loads(frag),
            "node_id": self.node_id,
            "cached": cached,
            "inference_time_us": time_us,
        }

    def handle_infer_raw(self, request: dict) -> bytes:
        """handle_infer, already serialized: the full response JSON built by
        splicing the cached output fragment — no float re-encoding."""
        request_id, frag, cached, time_us = self._infer_core(request)
        return (b'{"request_id": ' + json.dumps(request_id).encode()
                + b', "output_data": ' + frag
                + b', "node_id": ' + self._node_id_json
                + b', "cached": ' + (b"true" if cached else b"false")
                + b', "inference_time_us": ' + str(time_us).encode() + b"}")

    def _infer_unified(self) -> bool:
        """True when /infer misses ride the continuous scheduler as
        single-tick rows (unified stateless serving) instead of the
        legacy batch lane. Requires a scheduler that accepted an
        infer_engine — test fakes and non-continuous lanes fall back."""
        gen = self.generator
        return (self._unified and gen is not None
                and bool(getattr(gen, "accepts_oneshot", False)))

    def _score_unified(self) -> bool:
        gen = self.generator
        return (self._unified and gen is not None
                and bool(getattr(gen, "accepts_score", False)))

    def _dispatch_infer(self, item: _BatchItem,
                        deadline: Optional[Deadline]) -> _BatchResult:
        """Miss-path dispatch seam: the unified lane submits one
        single-tick scheduler row (one slot pool shared with decode
        streams — same deadlines, brownout, shedding, counters); legacy
        lanes keep the dedicated batch processor. Result and exception
        surface (DeadlineExceeded, engine errors) are identical either
        way, so the coalescing/cache/EWMA machinery upstream never knows
        which lane answered."""
        if not self._infer_unified():
            return self.batch_processor.process(item, deadline=deadline)
        sink = (TraceSink(self.tracer, self.node_id, item.request_id,
                          item.trace)
                if getattr(item, "trace", None) is not None else None)
        fut = self.generator.submit_infer(
            item.input_data, shape=item.shape, deadline=deadline,
            sink=sink, tag=item.request_id)
        out, time_us = fut.result(
            timeout=(600.0 if deadline is None
                     else max(5.0, deadline.remaining_s() + 5.0)))
        return _BatchResult(out, time_us)

    def _batch_observer(self, items, timing) -> None:
        """BatchProcessor tracing hook (dispatch thread): per-request
        ``queue_wait`` spans plus one shared ``batch_form`` span per
        member — the in-queue portion of latency the flat recorder could
        never attribute. Runs after the batch's futures resolve; span
        wall-clock is reconstructed from the observer call time."""
        end_wall = time.time()
        formed_at = end_wall - timing.compute_us / 1e6
        for it, wait_us in zip(items, timing.queue_wait_us):
            ctx = getattr(it, "trace", None)
            if ctx is None:
                continue
            qw = ctx.child()
            self.tracer.record(
                it.request_id, "queue_wait", self.node_id, wait_us,
                trace_id=qw.trace_id, span_id=qw.span_id,
                parent_id=ctx.span_id, start_ts=formed_at - wait_us / 1e6)
            bf = ctx.child()
            self.tracer.record(
                it.request_id, "batch_form", self.node_id,
                timing.batch_form_us, batch_size=len(items),
                trace_id=bf.trace_id, span_id=bf.span_id,
                parent_id=ctx.span_id,
                start_ts=formed_at - timing.batch_form_us / 1e6,
                attrs={"timed_out": timing.timed_out})

    def _record_device_spans(self, items, elapsed_us: float,
                             op: str = "device_compute") -> None:
        """One ``device_compute`` child span per traced batch member —
        duration is the whole batch's device leg (the exact measurement
        ``inference_time_us`` divides by batch size), batch_size carries
        the divisor."""
        start_wall = time.time() - elapsed_us / 1e6
        n = len(items)
        for it in items:
            ctx = getattr(it, "trace", None)
            if ctx is None:
                continue
            child = ctx.child()
            self.tracer.record(
                it.request_id, op, self.node_id, elapsed_us, batch_size=n,
                trace_id=child.trace_id, span_id=child.span_id,
                parent_id=ctx.span_id, start_ts=start_wall)

    def _process_batch(self, items: List[_BatchItem]) -> List[_BatchResult]:
        """Lockstep path — runs only when the engine lacks batch_submit
        (plain/fake engines); pipelined engines use _submit/_collect below."""
        start = time.perf_counter()
        shapes = ([it.shape for it in items]
                  if any(it.shape is not None for it in items) else None)
        outputs = self.engine.batch_predict(
            [it.input_data for it in items], shapes=shapes)
        elapsed_us = (time.perf_counter() - start) * 1e6
        per_request_us = int(elapsed_us / max(1, len(items)))
        self._record_device_spans(items, elapsed_us)
        return [_BatchResult(out, per_request_us) for out in outputs]

    def _submit_batch(self, items: List[_BatchItem]):
        """Pipeline dispatch half: stage + enqueue device work, no blocking.
        The batcher keeps `pipeline_depth` of these in flight so round-trips
        to the device overlap instead of serializing."""
        start = time.perf_counter()
        shapes = ([it.shape for it in items]
                  if any(it.shape is not None for it in items) else None)
        handle = self.engine.batch_submit(
            [it.input_data for it in items], shapes=shapes)
        return handle, start, items

    def _collect_batch(self, submitted) -> List[_BatchResult]:
        """Blocking half. `inference_time_us` semantics differ deliberately
        from the reference (worker_node.cpp:123 divides the bare execute
        time): here elapsed spans submit→collect, i.e. the batch's full
        residence in the device pipeline, including transfer and the
        overlap window behind up to pipeline_depth-1 older batches. That is
        the latency a caller actually experienced for the device leg; the
        execute-only number would undercount on a link-dominated setup."""
        handle, start, items = submitted
        outputs = self.engine.batch_collect(handle)
        elapsed_us = (time.perf_counter() - start) * 1e6
        per_request_us = int(elapsed_us / max(1, len(items)))  # cf. worker_node.cpp:123
        self._record_device_spans(items, elapsed_us)
        return [_BatchResult(out, per_request_us) for out in outputs]

    # -- generation path -------------------------------------------------------

    def handle_generate(self, request: dict) -> dict:
        """Serve one /generate payload: autoregressive decode with batching.

        Wire: {request_id, prompt_tokens, max_new_tokens?, eos_id?,
        temperature?, seed?} → {request_id, tokens, node_id,
        generate_time_us}. No reference counterpart (the reference can only
        run one-shot graphs); field style matches /infer.
        """
        if self.generator is None or getattr(self.generator,
                                             "_stateless", False):
            # A stateless-family lane DOES carry a continuous scheduler
            # (its rows are all one-shot), but that is not a generation
            # lane — keep the reference wire contract (ValueError → 400).
            raise ValueError(f"model '{self.config.model}' does not support generation")
        if self._injected_fault is not None:
            raise RuntimeError(f"fault injected: {self._injected_fault}")
        self._check_model(request)
        deadline = Deadline.from_request(request)
        tier = self._request_tier(request)
        with self._traced_request(request, "generate") as span:
            with self._admitted(deadline, trace=(span.ctx,
                                                 span.request_id),
                                tier=tier):
                return self._generate_admitted(request, deadline,
                                               span.ctx, tier=tier)

    def _generate_admitted(self, request: dict,
                           deadline: Optional[Deadline],
                           tctx: TraceContext,
                           tier: Optional[int] = None) -> dict:
        with self._counter_lock:
            self._total_requests += 1
        item = _GenItem(
            request_id=request["request_id"],
            prompt=[int(t) for t in request["prompt_tokens"]],
            max_new_tokens=self._brownout_clamp(
                int(request.get("max_new_tokens", 32)), tier),
            eos_id=int(request.get("eos_id", -1)),
            temperature=float(request.get("temperature", 0.0)),
            seed=int(request.get("seed", 0)),
            top_p=float(request.get("top_p", 1.0)),
            top_k=_clamp_top_k(request.get("top_k", 0)),
            repetition_penalty=float(
                request.get("repetition_penalty", 1.0)),
            stop_tokens=tuple(int(t)
                              for t in request.get("stop_tokens", ())),
            beam_width=int(request.get("beam_width", 1)),
            length_penalty=float(request.get("length_penalty", 1.0)),
            min_p=_validate_min_p(request.get("min_p", 0.0)),
            trace=tctx,
        )
        self._validate_beam(item.beam_width, item.temperature, item.top_p,
                            item.top_k, item.repetition_penalty,
                            item.stop_tokens, item.length_penalty,
                            item.min_p)
        # Validate stopping params BEFORE the item can join a shared batch
        # — a malformed request must 400 alone, never poison its
        # co-batched group (the batch lane would otherwise surface
        # expand_stopping_params' error to every request in the group).
        expand_stopping_params(1, item.repetition_penalty,
                               [list(item.stop_tokens)]
                               if item.stop_tokens else None)
        if self._speculative and (item.top_p < 1.0 or item.top_k > 0
                                  or item.repetition_penalty != 1.0
                                  or item.min_p > 0):
            # Reject BEFORE the item enters a shared batch: rejection
            # sampling is exact for the temperature distribution only, and
            # one filtered request must not poison its co-batched group.
            raise ValueError(
                "speculative scheduler supports temperature sampling only "
                "(top_p/top_k/repetition_penalty unavailable; use "
                "gen_scheduler=continuous)")
        if self._continuous:
            t0 = time.perf_counter()
            fut = self.generator.submit(
                item.prompt, max_new_tokens=item.max_new_tokens,
                eos_id=item.eos_id, temperature=item.temperature,
                seed=item.seed, top_p=item.top_p, top_k=item.top_k,
                repetition_penalty=item.repetition_penalty,
                stop_tokens=list(item.stop_tokens), min_p=item.min_p,
                deadline=deadline,
                sink=TraceSink(self.tracer, self.node_id,
                               item.request_id, tctx),
                tag=item.request_id,
                # Fleet prefix tier: the gateway-attached hint rides
                # the payload; inert unless --prefix-fetch is on.
                prefix_hint=(request.get("prefix_hint")
                             if self.config.gen_prefix_fetch else None))
            # The scheduler itself cancels expired rows between chunks
            # (the future then raises DeadlineExceeded); the +5 s slack
            # keeps this outer wait a backstop, never the arbiter.
            tokens = fut.result(
                timeout=600 if deadline is None
                else max(5.0, deadline.remaining_s() + 5.0))
            elapsed_us = int((time.perf_counter() - t0) * 1e6)
            result = _GenResult(tokens, elapsed_us)
        else:
            result = self._gen_processor.process(item, deadline=deadline)
        return {
            "request_id": item.request_id,
            "tokens": result.tokens,
            "node_id": self.node_id,
            "generate_time_us": result.generate_time_us,
        }

    def handle_generate_stream(self, request: dict):
        """Streaming /generate: returns an iterator of SSE event byte
        chunks. Under the continuous scheduler tokens stream at
        iteration-level granularity (fresh tokens after each decode chunk);
        under the batch scheduler the full result arrives as one event —
        same wire contract, coarser cadence. Events:

          data: {"tokens": [..]}          incremental tokens
          data: {"done": true, "request_id", "tokens", "node_id",
                 "generate_time_us"}      terminal summary (or "error")
        """
        if self.generator is None or getattr(self.generator,
                                             "_stateless", False):
            # Same contract as handle_generate: a stateless-family
            # lane's scheduler has no decode loop to stream from.
            raise ValueError(
                f"model '{self.config.model}' does not support generation")
        if self._injected_fault is not None:
            raise RuntimeError(f"fault injected: {self._injected_fault}")
        self._check_model(request)
        # Deadline/admission EAGERLY too: an expired or shed request must
        # 503 before the 200 SSE stream is committed.
        deadline = Deadline.from_request(request)
        # Parse/validate EVERY field EAGERLY — after the iterator is handed
        # back, the response is already committed to a 200 SSE stream, and a
        # bad request must be a 400 like the blocking endpoint's (on both
        # scheduler paths).
        request_id = request["request_id"]
        if request.get("migrate_import") is not None:
            # Live stream migration continuation: the snapshot carries
            # every decode parameter — the surrounding payload's fields
            # are routing metadata only.
            return self._stream_import(request, deadline,
                                       self._request_tier(request))
        prompt = [int(t) for t in request["prompt_tokens"]]
        tier = self._request_tier(request)
        max_new = self._brownout_clamp(
            int(request.get("max_new_tokens", 32)), tier)
        eos_id = int(request.get("eos_id", -1))
        temperature = float(request.get("temperature", 0.0))
        seed = int(request.get("seed", 0))
        top_p = float(request.get("top_p", 1.0))
        top_k = _clamp_top_k(request.get("top_k", 0))
        rep_pen = float(request.get("repetition_penalty", 1.0))
        stop_toks = [int(t) for t in request.get("stop_tokens", ())]
        beam_width = int(request.get("beam_width", 1))
        length_penalty = float(request.get("length_penalty", 1.0))
        min_p_val = _validate_min_p(request.get("min_p", 0.0))
        # Same eager validation as the blocking endpoint: a malformed
        # request must 400 before the 200 SSE stream is committed.
        expand_stopping_params(1, rep_pen,
                               [stop_toks] if stop_toks else None)
        self._validate_beam(beam_width, temperature, top_p, top_k,
                            rep_pen, stop_toks, length_penalty, min_p_val)
        if self._speculative and (top_p < 1.0 or top_k > 0
                                  or rep_pen != 1.0 or min_p_val > 0):
            # Must fire HERE, before the iterator commits a 200 SSE stream
            # — same 400 the blocking endpoint gives this payload.
            raise ValueError(
                "speculative scheduler supports temperature sampling only "
                "(top_p/top_k/repetition_penalty unavailable; use "
                "gen_scheduler=continuous)")
        normalized = {"request_id": request_id, "prompt_tokens": prompt,
                      "max_new_tokens": max_new, "eos_id": eos_id,
                      "temperature": temperature, "seed": seed,
                      "top_p": top_p, "top_k": top_k,
                      "repetition_penalty": rep_pen,
                      "stop_tokens": stop_toks,
                      "beam_width": beam_width,
                      "length_penalty": length_penalty,
                      "min_p": min_p_val}
        if "priority" in request:
            # Tiered admission / brownout clamping must see the tier on
            # the one-shot path's inner handle_generate too.
            normalized["priority"] = request["priority"]
        if deadline is not None:
            # Forward the REMAINING budget (deadline propagation).
            normalized["deadline_ms"] = max(0.0, deadline.remaining_ms())
        if not self._continuous:
            # Eager shed check so drain/overload/expired 503s BEFORE the
            # 200 SSE stream commits (same contract as the continuous
            # path below); released immediately — handle_generate admits
            # for real on first iteration, and a shed that slips into the
            # gap still surfaces as the stream's terminal error event.
            self._admission.admit(deadline, tier=tier)
            self._admission.release()
            one_shot_parent = TraceContext.from_request(request)
            one_shot_ctx = (one_shot_parent.child()
                            if one_shot_parent is not None
                            else TraceContext.root(request_id))

            def one_shot():
                try:
                    # handle_generate admits (depth/drain/deadline) itself.
                    result = self.handle_generate(normalized)
                except Exception as exc:  # terminal error event, stream ends
                    yield sse_event(self._stream_error(
                        exc, request_id, one_shot_ctx.trace_id, 0))
                    return
                yield sse_event({"tokens": result["tokens"]})
                yield sse_event({"done": True, **result})
            return one_shot()

        # Continuous path: admit before the stream commits; depth is held
        # until the event iterator finishes (the stream IS the in-flight
        # work). An expired deadline raises here -> wire 503, not a 200.
        parent = TraceContext.from_request(request)
        tctx = (parent.child() if parent is not None
                else TraceContext.root(request_id))
        t_start_wall = time.time()
        t_admit = time.perf_counter()
        self._admission.admit(deadline, tier=tier)
        try:
            self._maybe_slow()
            with self._counter_lock:
                self._total_requests += 1
            q: "queue.Queue" = queue.Queue()
            t0 = time.perf_counter()
            # Disaggregated handoff (gateway-stamped): park the row
            # after prefill for the export-after-prefill command; the
            # park window bounds how long a row can wait before local
            # decode resumes (the colocated fallback).
            handoff_kw = {}
            if request.get("handoff") and hasattr(self.generator,
                                                  "export_row"):
                # Clamped: a client-supplied park window must never pin
                # a slot + KV chain indefinitely (the scheduler clamps
                # again as a backstop).
                handoff_kw = {
                    "handoff": True,
                    "handoff_park_s": min(120.0, max(
                        0.1,
                        float(request.get("handoff_park_ms",
                                          5000.0)) / 1000.0))}
            fut = self.generator.submit(
                prompt, max_new_tokens=max_new, eos_id=eos_id,
                temperature=temperature, seed=seed, top_p=top_p, top_k=top_k,
                repetition_penalty=rep_pen, stop_tokens=stop_toks,
                min_p=min_p_val, stream=q, deadline=deadline,
                sink=TraceSink(self.tracer, self.node_id, request_id, tctx),
                tag=request_id,
                # Fleet prefix tier: the gateway-attached hint rides
                # the payload; inert unless --prefix-fetch is on.
                prefix_hint=(request.get("prefix_hint")
                             if self.config.gen_prefix_fetch else None),
                **handoff_kw)
        except BaseException:
            self._admission.release()
            raise
        return self._continuous_stream_events(
            q, fut, request_id, tctx, parent, t0, t_start_wall, t_admit)

    def _stream_import(self, request: dict,
                       deadline: Optional[Deadline], tier: Optional[int]):
        """Continuation half of live stream migration: adopt an exported
        row (the ``migrate_import`` snapshot) and stream its REMAINING
        tokens — no prefill, no re-emitted prefix. Rides the normal
        /generate/stream surface so the gateway journal splices it like
        any other segment, and admission applies like any stream (a
        draining or overloaded destination sheds 503 before the 200
        commits — the orchestrator's fallback ladder handles it)."""
        gen = self.generator
        if gen is None or not hasattr(gen, "submit_import"):
            raise ValueError(
                "migrate_import requires a continuous-scheduler lane "
                "with the paged KV cache")
        request_id = request["request_id"]
        snap = request["migrate_import"]
        parent = TraceContext.from_request(request)
        if parent is None and isinstance(snap, dict):
            # Cross-lane trace stitching: an export snapshot from a
            # --trace-stitch lane carries the exporting row's trace
            # context even when the dispatch payload itself is
            # traceless — the adopted row's spans re-parent under the
            # SAME trace the source lane recorded (additive snapshot
            # key; absent on un-stitched exports).
            parent = TraceContext.from_request(snap)
        tctx = (parent.child() if parent is not None
                else TraceContext.root(request_id))
        t_start_wall = time.time()
        t_admit = time.perf_counter()
        self._admission.admit(deadline, tier=tier)
        try:
            self._maybe_slow()
            with self._counter_lock:
                self._total_requests += 1
            q: "queue.Queue" = queue.Queue()
            t0 = time.perf_counter()
            # ValueError (malformed snapshot) raises HERE -> wire 400
            # before the 200 SSE stream commits.
            fut = gen.submit_import(
                snap, stream=q, deadline=deadline,
                sink=TraceSink(self.tracer, self.node_id, request_id,
                               tctx),
                tag=request_id)
        except BaseException:
            self._admission.release()
            raise
        return self._continuous_stream_events(
            q, fut, request_id, tctx, parent, t0, t_start_wall, t_admit)

    def _continuous_stream_events(self, q, fut, request_id, tctx, parent,
                                  t0, t_start_wall, t_admit):
        """The continuous-scheduler SSE event iterator, shared by fresh
        submissions and migration imports. Owns the admission release."""
        def events():
            sent = 0  # tokens relayed to the client so far (resume offset)
            completed = False
            try:
                while True:
                    try:
                        item = q.get(timeout=600)
                    except queue.Empty:
                        self._segment_span(request_id, tctx, parent, t0,
                                           t_start_wall, "stalled")
                        yield sse_event(self._stream_error(
                            RuntimeError("generation stalled (no tokens "
                                         "for 600s)"),
                            request_id, tctx.trace_id, sent))
                        return
                    if item is None:
                        break
                    sent += len(item)
                    yield sse_event({"tokens": item})
                elapsed_us = int((time.perf_counter() - t0) * 1e6)
                try:
                    tokens = fut.result(timeout=10)
                except Exception as exc:
                    self._segment_span(
                        request_id, tctx, parent, t0, t_start_wall,
                        "exported" if getattr(exc, "migrated", False)
                        else "error")
                    yield sse_event(self._stream_error(
                        exc, request_id, tctx.trace_id, sent))
                    return
                self.tracer.record(
                    request_id, "generate_stream", self.node_id,
                    elapsed_us, trace_id=tctx.trace_id,
                    span_id=tctx.span_id,
                    parent_id=(parent.span_id if parent is not None
                               else None),
                    start_ts=t_start_wall)
                completed = True
                yield sse_event({"done": True, "request_id": request_id,
                                 "tokens": tokens, "node_id": self.node_id,
                                 "generate_time_us": elapsed_us})
            finally:
                self._admission.release()
                # Streams feed the AIMD window too (admit -> clean
                # finish) — on a stream-only lane the limit must still
                # see the latency it exists to react to.
                if completed and self._aimd is not None:
                    self._aimd.observe(time.perf_counter() - t_admit)
        return events()

    def _segment_span(self, request_id, tctx, parent, t0, t_start_wall,
                      outcome: str) -> None:
        """Root span for a stream SEGMENT that did not complete on this
        lane (exported row, lane fault, stall). The stage spans already
        recorded under ``tctx.span_id`` must not dangle: a mobile
        stream's stitched tree needs every serving lane's segment root,
        and even a single lane's /trace/export should never ship
        orphans (the completion path records the same span with no
        ``segment`` attr)."""
        self.tracer.record(
            request_id, "generate_stream", self.node_id,
            (time.perf_counter() - t0) * 1e6,
            trace_id=tctx.trace_id, span_id=tctx.span_id,
            parent_id=(parent.span_id if parent is not None else None),
            start_ts=t_start_wall, attrs={"segment": outcome})

    @staticmethod
    def _stream_error(exc: BaseException, request_id: str, trace_id: str,
                      tokens_emitted: int) -> dict:
        """Terminal error event for a failed stream — no longer opaque: it
        carries everything a client (or the gateway's stream journal)
        needs to RESUME the generation elsewhere. ``retryable``
        distinguishes lane faults (another lane can continue the stream
        byte-identically) from spent budgets and bad requests;
        ``tokens_emitted`` is the resume offset (prompt ⧺ that many
        already-received tokens); ``trace_id`` joins the event to the
        request's trace tree. An exception may pre-classify itself with a
        ``retryable`` attribute (the scheduler's _recover row events do)."""
        retryable = getattr(exc, "retryable", None)
        if retryable is None:
            if isinstance(exc, DeadlineExceeded):
                retryable = False  # the budget is spent: no lane can help
            elif isinstance(exc, ShedError):
                retryable = True   # overload/drain: healthy lanes elsewhere
            elif isinstance(exc, (KeyError, ValueError, TypeError)):
                retryable = False  # the request is at fault
            else:
                retryable = True   # lane/device fault
        out = {"done": True, "error": str(exc)[:300],
               "retryable": bool(retryable),
               "request_id": request_id, "trace_id": trace_id,
               "tokens_emitted": int(tokens_emitted)}
        if getattr(exc, "migrated", False):
            # The row was EXPORTED (live stream migration): the
            # gateway's journal splices the destination's continuation
            # instead of replay-resuming; a journal-less client can
            # still resume manually like any retryable terminal.
            out["migrated"] = True
        if getattr(exc, "import_refused", False):
            # A migration import THIS lane refused post-splice
            # (checksum, geometry, pool pressure): the gateway counts
            # the replay fallback against migration, not the lane.
            out["import_refused"] = True
        if isinstance(exc, ShedError):
            # Policy refusal from a HEALTHY lane: the gateway's failover
            # journal resumes these WITHOUT a breaker penalty (the same
            # shed-vs-fault split _try_node applies at admission).
            out["shed"] = True
        return out

    def _process_gen_batch(self, items: List[_GenItem]) -> List[_GenResult]:
        """Group by eos_id (a compile-time scalar of the decode executable);
        temperature and seed are per-row vectors, so mixed sampling params
        share one compiled batch. The batch runs to the group's max
        max_new_tokens; per-request counts are truncated after."""
        results: List[Optional[_GenResult]] = [None] * len(items)
        groups = {}
        for idx, it in enumerate(items):
            if it.beam_width > 1:
                # Beam requests run alone (beams occupy the batch axis).
                t0 = time.perf_counter()
                row = self.generator.beam_search(
                    it.prompt, beam_width=it.beam_width,
                    max_new_tokens=it.max_new_tokens, eos_id=it.eos_id,
                    length_penalty=it.length_penalty)
                results[idx] = _GenResult(
                    row[: it.max_new_tokens],
                    int((time.perf_counter() - t0) * 1e6))
                continue
            groups.setdefault(it.eos_id, []).append(idx)
        for eos_id, idxs in groups.items():
            t0 = time.perf_counter()
            max_new = max(items[i].max_new_tokens for i in idxs)
            toks = self.generator.generate(
                [items[i].prompt for i in idxs], max_new_tokens=max_new,
                eos_id=eos_id,
                temperature=[items[i].temperature for i in idxs],
                seed=[items[i].seed for i in idxs],
                top_p=[items[i].top_p for i in idxs],
                top_k=[items[i].top_k for i in idxs],
                repetition_penalty=[items[i].repetition_penalty
                                    for i in idxs],
                stop_tokens=[list(items[i].stop_tokens) for i in idxs],
                min_p=[items[i].min_p for i in idxs],
                # The speculative generator is single-dispatch by design
                # and takes no fused flag.
                **({} if self._speculative
                   else {"fused": self.config.gen_decode_fused}))
            group_elapsed_us = (time.perf_counter() - t0) * 1e6
            self._record_device_spans([items[i] for i in idxs],
                                      group_elapsed_us)
            # Reference semantic: per-request time = batch_duration /
            # batch_size, per group (worker_node.cpp:123).
            elapsed_us = int(group_elapsed_us / max(1, len(idxs)))
            for i, row in zip(idxs, toks):
                results[i] = _GenResult(row[: items[i].max_new_tokens], elapsed_us)
        return results

    # -- observability --------------------------------------------------------

    def latency_histograms(self) -> dict:
        """Named Prometheus histograms beyond the stage-latency family:
        the decode lane's TTFT and inter-token-latency distributions
        (`utils.metrics.render_named_histograms` renders them at
        /metrics). Empty for lanes without a continuous scheduler."""
        gen = self.generator
        if gen is None or not hasattr(gen, "ttft_hist"):
            return {}
        if getattr(gen, "_stateless", False):
            # One-shot rows have no first-token or inter-token moments;
            # a stateless-family lane keeps its /metrics text identical
            # to the retired batch lane's.
            return {}
        return {
            "tpu_engine_ttft_seconds": {self.node_id: gen.ttft_hist},
            "tpu_engine_itl_seconds": {self.node_id: gen.itl_hist},
        }

    def get_health(self) -> dict:
        """Exact /health schema (``worker_node.cpp:85-103``)."""
        m = self.batch_processor.get_metrics()
        with self._counter_lock:
            total, hits = self._total_requests, self._cache_hits
        if self.external_counters is not None:
            ext_total, ext_hits = self.external_counters()
            total += ext_total
            hits += ext_hits
        out = {
            "healthy": self._injected_fault is None,
            "node_id": self.node_id,
            "model": getattr(self.engine.spec, "name", None),  # additive
            "total_requests": total,
            "cache_hits": hits,
            "cache_size": self.cache.size(),
            "cache_hit_rate": self.cache.hit_rate(),
            "batch_processor": m.as_dict(),
        }
        if self.config.role != "both":
            # Additive, and only for dedicated-role lanes: a default
            # fleet's /health stays byte-identical (absent key = "both"
            # — the gateway's role discovery reads it that way).
            out["role"] = self.config.role
        if int(self.config.tp) > 1:
            # Additive topology label (absent key = one chip — the
            # gateway's topology-aware ring reads it that way): this
            # lane spans a `model`-axis mesh slice of tp devices, so
            # its virtual nodes should carry a per-chip weight instead
            # of one lane == one chip.
            from tpu_engine.parallel.mesh import tp_topology_label

            out["topology"] = tp_topology_label(self.config.tp)
        # Additive (reference schema untouched — its parsers ignore extra
        # keys): decode-lane scheduler counters for transformer workers.
        if self.generator is not None and hasattr(self.generator, "stats"):
            try:
                gstats = self.generator.stats()
            except Exception:
                gstats = None
            if gstats is not None:
                if getattr(self.generator, "_stateless", False):
                    # Unified stateless serving on a stateless-family
                    # lane: the scheduler IS the batch lane now, so its
                    # one-shot dispatch counters FOLD into the
                    # wire-exact 4-key batch_processor block instead of
                    # growing /health a "generator" key the reference
                    # schema (worker_node.cpp:85-103) never had. A
                    # defaults-on mlp lane answers byte-compatible.
                    st = gstats.get("stateless") or {}
                    bp = out["batch_processor"]
                    rows = (int(st.get("infer_rows", 0))
                            + int(st.get("score_rows", 0)))
                    disp = int(st.get("dispatches", 0))
                    prev_rows = (float(bp["avg_batch_size"])
                                 * int(bp["total_batches"]))
                    bp["total_batches"] = int(bp["total_batches"]) + disp
                    bp["full_batches"] = (int(bp["full_batches"])
                                          + int(st.get("full_dispatches",
                                                       0)))
                    if bp["total_batches"] > 0:
                        bp["avg_batch_size"] = ((prev_rows + rows)
                                                / bp["total_batches"])
                else:
                    out["generator"] = gstats
                # Scheduler liveness: a wedged decode loop (stuck inside a
                # device dispatch) is process-alive but cannot serve —
                # last-tick age is the only signal that sees it. With
                # scheduler_stall_s > 0 a stale loop flips the lane
                # unhealthy, so the gateway's prober ejects it like a
                # dead process instead of breakers tripping one victim
                # request at a time.
                age = gstats.get("last_tick_age_s")
                stall = float(self.config.scheduler_stall_s or 0.0)
                if stall > 0 and age is not None and age > stall:
                    out["healthy"] = False
                    out["scheduler_stalled"] = True
        # Fleet prefix tier seed (additive, gated on --prefix-fetch so
        # defaults-off /health bytes stay identical): bounded top-K
        # radix chain summaries the gateway prober turns into directory
        # entries — never a full-tree dump.
        if (self.config.gen_prefix_fetch and self.generator is not None
                and hasattr(self.generator, "prefix_fingerprints")):
            try:
                out["prefix_fingerprints"] = \
                    self.generator.prefix_fingerprints()
            except Exception:
                pass
        # Additive, and only once admission control has anything to say
        # (a defaults-only lane keeps the reference-exact key set).
        dropped = self.batch_processor.deadline_dropped
        if self._gen_processor is not None:
            dropped += self._gen_processor.deadline_dropped
        score_proc = getattr(self, "_score_proc", None)
        if score_proc is not None:
            dropped += score_proc.deadline_dropped
        if self._infer_unified() or self._score_unified():
            # One-shot rows the scheduler cancelled at their deadline
            # count exactly like the retired batch lane's drops.
            try:
                dropped += int((self.generator.stats().get("stateless")
                                or {}).get("deadline_dropped", 0))
            except Exception:
                pass
        if self._admission.active or dropped:
            adm = self._admission.as_dict()
            adm["deadline_dropped"] = dropped
            out["admission"] = adm
        # Additive, gated on the flag: the staged brownout controller's
        # current stage, pressure, and transition counters.
        if self._brownout is not None:
            bo = self._brownout.as_dict()
            bo["clamped_requests"] = self._brownout_clamps
            out["brownout"] = bo
        return out

    def stop(self) -> None:
        self._brownout_stop.set()
        if self._brownout_thread is not None:
            self._brownout_thread.join(timeout=5)
            self._brownout_thread = None
        self.batch_processor.stop()
        if getattr(self, "_score_proc", None) is not None:
            self._score_proc.stop()
        if self._gen_processor is not None:
            self._gen_processor.stop()
        if self._continuous and self.generator is not None:
            self.generator.stop()
