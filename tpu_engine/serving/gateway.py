"""Gateway: consistent-hash routing with circuit-breaker-guarded failover.

Capability parity with the reference gateway
(``/root/reference/src/gateway.cpp``): requests route to the lane owning
``request_id`` on the hash ring (``:41``); on failure every other lane is
tried in ring order (``:51-59``); each lane is guarded by a circuit breaker
(5 failures / 2 successes / 30 s, ``:19-23``); ``get_stats`` exposes the
exact ``/stats`` schema (``:63-77``).

TPU-native shape: lanes are in-process dispatch targets over the chips of a
``jax.sharding.Mesh`` (``LocalWorkerClient``) — the reference's HTTP
fan-out becomes a function call and the scatter/gather rides ICI inside the
compiled executable. The HTTP client mode keeps the reference's
multi-process/multi-host deployment working unchanged (DCN between hosts).

Improvements over the reference (documented, not silent):
- elastic membership: ``add_worker``/``remove_worker`` at runtime (the
  reference's ring had removeNode but no caller — dead workers needed a
  gateway restart, ``README.md:336-339``);
- routing falls back to a random key when ``request_id`` is absent instead
  of raising.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Union

from tpu_engine.core.circuit_breaker import CircuitBreaker
from tpu_engine.core.consistent_hash import ConsistentHash
from tpu_engine.serving.clients import (
    HttpWorkerClient,
    LocalWorkerClient,
    WorkerError,
)
from tpu_engine.utils.config import GatewayConfig


class GatewayError(Exception):
    pass


class Gateway:
    def __init__(self, workers=None, config: Optional[GatewayConfig] = None):
        """``workers``: list of worker URLs (HTTP mode), WorkerNode objects
        (local mode), or a mix."""
        self.config = config or GatewayConfig()
        self._ring = ConsistentHash(self.config.virtual_nodes)
        # Multi-model serving: one sub-ring per model name so a request's
        # "model" field restricts routing AND failover to lanes that
        # actually serve it (Triton-style; the reference is one model per
        # worker with no model awareness at the gateway).
        self._model_rings: Dict[str, ConsistentHash] = {}
        # Workers with UNKNOWN model (HTTP URLs carry no metadata): while
        # any exist, an unmatched "model" falls back to the global ring
        # with worker-side validation instead of a 400 — they might serve
        # it.
        self._untyped: set = set()
        self._clients: Dict[str, object] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()
        self._total_requests = 0
        self._failovers = 0
        # Requests without a "model" field in multi-model mode route to
        # the first-registered model (deterministic default) instead of
        # whichever lane the global ring happens to own.
        self.default_model: Optional[str] = None
        for w in workers or []:
            self.add_worker(w)

    # -- membership (elastic; reference ring was fixed at launch) ------------

    def add_worker(self, worker) -> str:
        model_name = None
        if isinstance(worker, str):
            client = HttpWorkerClient(
                worker,
                timeout_s=self.config.worker_timeout_s,
                default_port=self.config.default_worker_port,
                gen_timeout_s=self.config.gen_timeout_s,
            )
            name = client.url
        else:
            client = LocalWorkerClient(worker)
            name = worker.node_id
            spec = getattr(getattr(worker, "engine", None), "spec", None)
            model_name = getattr(spec, "name", None)
        with self._lock:
            self._clients[name] = client
            self._breakers[name] = self._make_breaker()
            if model_name is None:
                self._untyped.add(name)
        self._ring.add_node(name)
        if model_name is not None:
            with self._lock:
                ring = self._model_rings.get(model_name)
                if ring is None:
                    # Populate BEFORE publishing: a concurrent _route must
                    # never see an empty ring for a registered model.
                    ring = ConsistentHash(self.config.virtual_nodes)
                    ring.add_node(name)
                    self._model_rings[model_name] = ring
                else:
                    ring.add_node(name)
                if self.default_model is None:
                    self.default_model = model_name
        return name

    def _make_breaker(self):
        """Native breaker when the C++ core is loaded — the native HTTP
        front shares the same breaker object for its hit-path gate."""
        try:
            from tpu_engine.core import native

            if native.available():
                return native.NativeCircuitBreaker(
                    self.config.failure_threshold,
                    self.config.success_threshold,
                    self.config.breaker_timeout_s,
                )
        except Exception:
            pass
        return CircuitBreaker(
            self.config.failure_threshold,
            self.config.success_threshold,
            self.config.breaker_timeout_s,
        )

    def breaker_for(self, name: str):
        with self._lock:
            return self._breakers.get(name)

    def remove_worker(self, name: str) -> None:
        self._ring.remove_node(name)
        with self._lock:
            rings = dict(self._model_rings)
            self._clients.pop(name, None)
            self._breakers.pop(name, None)
            self._untyped.discard(name)
        for ring in rings.values():
            ring.remove_node(name)
        with self._lock:
            # Prune emptied sub-rings and re-point the no-field default —
            # removing the default model's last lane must not strand every
            # field-less request on a dead ring forever.
            for mdl, ring in list(self._model_rings.items()):
                if not ring.get_all_nodes():
                    del self._model_rings[mdl]
            if self.default_model not in self._model_rings:
                self.default_model = (sorted(self._model_rings)[0]
                                      if self._model_rings else None)

    def worker_names(self) -> List[str]:
        return self._ring.get_all_nodes()

    # -- request path ---------------------------------------------------------

    def route_request(self, payload: dict) -> dict:
        return self._route(payload, op="infer")

    def route_request_raw(self, payload: dict) -> bytes:
        """Hot path: response stays pre-serialized bytes end-to-end (the
        reference re-parses and re-encodes the float array at every hop)."""
        return self._route(payload, op="infer_raw")

    def route_score(self, payload: dict) -> dict:
        """Route /score (teacher-forced logprobs) like /infer."""
        return self._route(payload, op="score")

    def route_generate(self, payload: dict) -> dict:
        """Route a /generate request the same way as /infer: ring primary,
        breaker-gated, ring-order failover."""
        return self._route(payload, op="generate")

    def route_generate_stream(self, payload: dict):
        """Streaming variant: same routing; the selected lane's SSE
        event-chunk iterator is handed back for chunked transfer. Breaker
        accounting happens at admission (iterator creation) — a mid-stream
        failure terminates that stream with an error event instead of
        failing over (tokens already sent can't be replayed elsewhere)."""
        return self._route(payload, op="generate_stream")

    def _route(self, payload: dict, op: str) -> dict:
        with self._lock:
            self._total_requests += 1
        request_id = str(payload.get("request_id", id(payload)))
        # "model" restricts routing AND failover to that model's sub-ring;
        # without the field, multi-model gateways use the deterministic
        # default model, single-model gateways the global ring.
        mdl = payload.get("model")
        probing = False  # model unknown to the gateway; workers validate
        with self._lock:
            multi = len(self._model_rings) > 1
            untyped = bool(self._untyped)
            if mdl is None and multi:
                mdl = self.default_model
            if mdl is not None:
                ring = self._model_rings.get(str(mdl))
                if ring is None and untyped:
                    # Workers with unknown models (HTTP URLs carry no
                    # metadata) might serve it: probe the global ring and
                    # let each worker's _check_model decide — a mismatch
                    # fails over instead of 400ing a servable request.
                    ring, probing = self._ring, True
            else:
                ring = self._ring
        if ring is None:
            raise ValueError(            # wire 400, not a lane failure
                f"unknown model '{mdl}'; serving "
                f"{sorted(self._model_rings)}")
        try:
            primary = ring.get_node(request_id)
        except RuntimeError:  # every lane of this model was removed
            raise GatewayError(f"no workers available for model '{mdl}'")

        result = self._try_node(primary, payload, op=op, probing=probing)
        if result is not None:
            return result
        with self._lock:
            self._failovers += 1
        # Ring-order failover across every other lane (gateway.cpp:51-59).
        for node in ring.get_all_nodes():
            if node == primary:
                continue
            result = self._try_node(node, payload, op=op, probing=probing)
            if result is not None:
                return result
        raise GatewayError("All workers failed or unavailable")

    def _try_node(self, node: str, payload: dict, op: str = "infer",
                  probing: bool = False) -> Optional[dict]:
        """Breaker-gated dispatch (reference tryNode, gateway.cpp:80-128).
        Returns None on failure so the caller can fail over. `probing`:
        the gateway couldn't resolve the request's model itself, so a
        worker's model-mismatch rejection (a client-class 4xx/ValueError)
        means "try the next lane" — no breaker penalty, no terminal 400."""
        with self._lock:
            client = self._clients.get(node)
            breaker = self._breakers.get(node)
        if client is None or breaker is None:
            return None
        if not breaker.allow_request():
            return None
        try:
            response = getattr(client, op)(payload)
            breaker.record_success()
            return response
        except WorkerError:
            breaker.record_failure()
            return None
        except ValueError:
            if probing:
                return None  # wrong-model lane; healthy — no penalty
            raise

    # -- observability --------------------------------------------------------

    def get_stats(self) -> dict:
        """Exact /stats schema (``gateway.cpp:63-77``)."""
        with self._lock:
            items = list(self._breakers.items())
            total, failovers = self._total_requests, self._failovers
        return {
            "total_workers": len(items),
            # Additive fields (reference /stats has only total_workers +
            # circuit_breakers; extra keys don't break its parsers).
            "total_requests": total,
            "failovers": failovers,
            "circuit_breakers": [
                {
                    "node": node,
                    "state": br.state_name(),
                    "failures": br.failure_count,
                    "successes": br.success_count,
                }
                for node, br in items
            ],
        }
