"""Gateway: consistent-hash routing with circuit-breaker-guarded failover.

Capability parity with the reference gateway
(``/root/reference/src/gateway.cpp``): requests route to the lane owning
``request_id`` on the hash ring (``:41``); on failure every other lane is
tried in ring order (``:51-59``); each lane is guarded by a circuit breaker
(5 failures / 2 successes / 30 s, ``:19-23``); ``get_stats`` exposes the
exact ``/stats`` schema (``:63-77``).

TPU-native shape: lanes are in-process dispatch targets over the chips of a
``jax.sharding.Mesh`` (``LocalWorkerClient``) — the reference's HTTP
fan-out becomes a function call and the scatter/gather rides ICI inside the
compiled executable. The HTTP client mode keeps the reference's
multi-process/multi-host deployment working unchanged (DCN between hosts).

Improvements over the reference (documented, not silent):
- elastic membership: ``add_worker``/``remove_worker`` at runtime (the
  reference's ring had removeNode but no caller — dead workers needed a
  gateway restart, ``README.md:336-339``), with an optional drain
  (lame-duck) mode for graceful removal;
- routing falls back to a random key when ``request_id`` is absent instead
  of raising;
- a resilience layer (``serving/resilience.py``, DESIGN.md "Request
  resilience"): per-request deadlines threaded edge→lane, failover under
  a global retry budget with exponential backoff + jitter, and hedged
  dispatch for idempotent ops — the slow-lane/overload story the
  breaker-only reference has no answer for. All knobs default
  off/permissive; with defaults the routing behavior and wire schemas are
  byte-identical to the reference parity described above;
- crash-tolerant streaming (``failover_streams``, DESIGN.md
  "Crash-tolerant streaming"): a /generate/stream journal that resumes a
  mid-stream lane failure on another ring lane (prompt ⧺ emitted tokens,
  budget offset) and splices the continuation byte-identically, plus a
  proactive /health prober (``health_probe_interval_s``) that ejects dead
  lanes from rotation in O(probe interval) and restores them on recovery.
  Both default off.
"""

from __future__ import annotations

import collections
import concurrent.futures
import json
import threading
import time
import uuid
from typing import Dict, List, Optional, Union

from tpu_engine.core.circuit_breaker import CircuitBreaker
from tpu_engine.core.consistent_hash import ConsistentHash
from tpu_engine.serving.clients import (
    HttpWorkerClient,
    LocalWorkerClient,
    WorkerError,
)
from tpu_engine.serving.http import sse_event
from tpu_engine.serving.overload import (
    OverloadCounters,
    SheddingStats,
    TenantRateLimiter,
    TIER_ADMIT_FRAC,
    TIER_NAMES,
    load_retry_after,
    parse_priority,
    tier_limit,
)
from tpu_engine.serving.prefix_directory import PrefixDirectory
from tpu_engine.serving.resilience import (
    AffinityCounters,
    FailoverCounters,
    FleetCounters,
    HandoffCounters,
    LatencyTracker,
    MigrationCounters,
    PrefixDirCounters,
    ProbeStateMachine,
    ResilienceCounters,
    RetryBudget,
    backoff_delay,
)
from tpu_engine.utils.config import GatewayConfig
from tpu_engine.utils.deadline import (
    Deadline,
    DeadlineExceeded,
    Overloaded,
    ShedError,
)
from tpu_engine.serving.slo import (
    OBJECTIVE_SOURCES,
    SloTracker,
    completion_hists,
)
from tpu_engine.utils.tracing import (
    SpanRecorder,
    TraceContext,
    stitch_trace,
)


class GatewayError(Exception):
    pass


# Ops safe to hedge: a duplicate dispatch returns the identical answer and
# costs only compute (cache-first /infer, teacher-forced /score). /generate
# is excluded — duplicating a whole decode loop is the one cost hedging
# must never pay, and a stream cannot be "first response wins".
_HEDGEABLE_OPS = frozenset({"infer", "infer_raw", "score"})

# _try_node outcome for a lane that SHED the request (overloaded/draining):
# failure for failover purposes, but distinguishable from a fault — if the
# WHOLE ring sheds, the request must surface as 503 + Retry-After
# (congestion), never the 500-class "all workers failed" (outage).
_SHED = object()


def _ok(result) -> bool:
    return result is not None and result is not _SHED


def _parse_sse(frame: bytes) -> Optional[dict]:
    """One SSE frame (``sse_event`` output) -> its JSON payload, or None
    for anything unparseable (relayed verbatim, never dropped)."""
    try:
        text = frame.decode()
    except Exception:
        return None
    text = text.strip()
    if not text.startswith("data: "):
        return None
    try:
        evt = json.loads(text[len("data: "):])
    except Exception:
        return None
    return evt if isinstance(evt, dict) else None


class _StreamRecord:
    """One journaled /generate/stream's migration state: which lane
    currently serves it, and the one-shot handoff slot the drain
    orchestrator fills (continuation iterator + destination lane) for
    the RELAY thread to splice. The handoff is an exchange with three
    terminal states — offered, failed, abandoned — resolved exactly
    once under ``_hlock``: an orchestrator whose offer loses the race
    against the relay's timeout must dispose of its continuation
    iterator itself (the relay has already moved on to the replay
    fallback)."""

    __slots__ = ("request_id", "payload", "deadline", "ctx", "lane",
                 "_hlock", "_ready", "_it", "_dest", "_error",
                 "_abandoned", "handoff", "spliced_handoff")

    def __init__(self, request_id: str, payload: dict, deadline, ctx,
                 lane: Optional[str]):
        self.request_id = request_id
        self.payload = payload
        self.deadline = deadline
        self.ctx = ctx
        self.lane = lane
        # Disaggregated serving: True while the steady-state
        # prefill→decode handoff orchestrator owns this stream's next
        # migrated terminal (counts into the `handoff` family, not
        # `migration`); cleared after the first splice so a LATER
        # drain-time migration counts normally. Written by the relay
        # thread and the stream's orchestrator only. `spliced_handoff`
        # remembers whether the LATEST splice was a handoff, so a
        # post-splice in-band import refusal attributes its fallback to
        # the right counter family.
        self.handoff = False
        self.spliced_handoff = False
        self._hlock = threading.Lock()
        self._ready = threading.Event()
        self._it = None
        self._dest: Optional[str] = None
        self._error: Optional[str] = None
        self._abandoned = False

    def offer(self, it, dest: str) -> bool:
        """Orchestrator: hand the continuation to the relay. False when
        the relay already abandoned the wait — the caller must dispose
        of ``it``."""
        with self._hlock:
            if self._abandoned or self._ready.is_set():
                return False
            self._it, self._dest = it, dest
            self._ready.set()
            return True

    def fail(self, reason: str) -> None:
        """Orchestrator: no continuation is coming — the relay falls
        back to the replay resume."""
        with self._hlock:
            if not self._abandoned and not self._ready.is_set():
                self._error = reason
                self._ready.set()

    def await_handoff(self, timeout_s: float):
        """Relay: block for the orchestrator's verdict. Returns
        (iterator, dest_lane) on success, None on failure or timeout —
        after None the slot is ABANDONED (a late offer is refused) and
        re-armed for a possible later migration. An offer that raced in
        between the Event timeout and this lock acquisition still WINS
        (the continuation exists — dropping it here would leak a live
        iterator and duplicate the decode on the replay lane)."""
        ok = self._ready.wait(timeout=max(0.0, timeout_s))
        with self._hlock:
            if self._it is not None:
                # Offered — possibly a hair after the wait timed out,
                # but before the relay could abandon: take it.
                out = (self._it, self._dest)
                self._abandoned = False
            else:
                out = None
                # Timed out with nothing offered: refuse late offers
                # (the orchestrator disposes). A FAILED handoff is
                # consumed, not abandoned.
                self._abandoned = not ok and self._error is None
            # Re-arm: this stream may be migrated again later.
            self._ready.clear()
            self._it = self._dest = self._error = None
            return out

    def rearm(self) -> None:
        """Relay: clear a stale abandonment before the next migration
        window (called when a new segment starts relaying)."""
        with self._hlock:
            if not self._ready.is_set():
                self._abandoned = False

    def pending_offer(self) -> bool:
        """True while an OFFERED continuation sits unconsumed — the
        drain orchestrator waits these out before returning (the caller
        is about to kill the source process; a relay that has not yet
        taken its handoff would read a dead socket first and replay)."""
        with self._hlock:
            return self._ready.is_set() and self._it is not None

    def take_unconsumed(self):
        """Stream teardown: pop an offered-but-never-consumed
        continuation (the relay ended another way) so the caller can
        dispose of it — an orphan iterator would pin the destination's
        admission depth."""
        with self._hlock:
            if self._ready.is_set() and self._it is not None:
                it = self._it
                self._it = self._dest = self._error = None
                self._ready.clear()
                return it
            return None


class _RouteTrace:
    """Per-request trace state threaded through the routing layers: the
    route span's context (every attempt / resilience-decision span parents
    here) and whether the CLIENT supplied a traceparent — only then is the
    context re-forwarded to workers, so traceless requests keep their wire
    bytes identical to the pre-tracing protocol (anonymous correlation
    rides the request_id-derived trace id instead)."""

    __slots__ = ("request_id", "parent", "ctx", "outcome")

    def __init__(self, request_id: str, parent: Optional[TraceContext]):
        self.request_id = request_id
        self.parent = parent
        self.ctx = (parent.child() if parent is not None
                    else TraceContext.root(request_id))
        self.outcome = "error"

    @property
    def traced(self) -> bool:
        return self.parent is not None


class _StreamLedger:
    """Which lanes served each request_id, hop by hop — the index the
    cross-lane trace stitcher (GET /admin/trace/<rid>) walks to know
    WHOSE ring buffers hold a mobile stream's span fragments. Mobility
    machinery records one entry per hop (admit / handoff / migrate /
    resume) at the exact points the stream's serving lane changes;
    entries OUTLIVE the stream record (stitching is a postmortem read).
    Bounded FIFO over request_ids; own lock (ledger writes happen inside
    relay loops that must never contend with routing's _lock)."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(1, int(capacity))
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self._llock = threading.Lock()

    def hop(self, request_id: str, lane: str, kind: str,
            trace_id: Optional[str] = None) -> None:
        with self._llock:
            ent = self._entries.get(request_id)
            if ent is None:
                while len(self._entries) >= self.capacity:
                    self._entries.popitem(last=False)
                ent = {"trace_id": trace_id, "hops": []}
                self._entries[request_id] = ent
            elif trace_id and not ent["trace_id"]:
                ent["trace_id"] = trace_id
            ent["hops"].append({"lane": lane, "kind": kind,
                                "ts": round(time.time(), 6)})

    def get(self, request_id: str) -> Optional[dict]:
        with self._llock:
            ent = self._entries.get(request_id)
            if ent is None:
                return None
            return {"trace_id": ent["trace_id"],
                    "hops": [dict(h) for h in ent["hops"]]}

    def summary(self) -> dict:
        with self._llock:
            return {"streams": len(self._entries),
                    "capacity": self.capacity,
                    "hops": sum(len(e["hops"])
                                for e in self._entries.values())}


class Gateway:
    def __init__(self, workers=None, config: Optional[GatewayConfig] = None):
        """``workers``: list of worker URLs (HTTP mode), WorkerNode objects
        (local mode), or a mix."""
        self.config = config or GatewayConfig()
        self._ring = ConsistentHash(self.config.virtual_nodes)
        # Multi-model serving: one sub-ring per model name so a request's
        # "model" field restricts routing AND failover to lanes that
        # actually serve it (Triton-style; the reference is one model per
        # worker with no model awareness at the gateway).
        self._model_rings: Dict[str, ConsistentHash] = {}
        # Workers with UNKNOWN model (HTTP URLs carry no metadata): while
        # any exist, an unmatched "model" falls back to the global ring
        # with worker-side validation instead of a 400 — they might serve
        # it.
        self._untyped: set = set()
        self._clients: Dict[str, object] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()
        self._total_requests = 0
        self._failovers = 0
        # Resilience layer (all knobs default off/permissive — see
        # GatewayConfig): deadline admission + budgeted, backed-off
        # failover + hedged dispatch, every decision counted.
        self.resilience = ResilienceCounters()
        self._retry_budget = RetryBudget(self.config.retry_budget_ratio,
                                         self.config.retry_budget_min,
                                         self.config.retry_budget_window_s)
        # PER-LANE latency windows: a global window would let a slow lane
        # receiving >(1-q) of traffic drag the hedge quantile up to its
        # own latency, self-disabling hedging for exactly the lane it
        # exists to cover.
        self._latency: Dict[str, LatencyTracker] = {}
        self._hedge_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        # Disagg handoff orchestrators get their own bounded executor
        # (created on first use): they block for whole prefill
        # durations and must not starve the hedge/drain pool.
        self._handoff_exec: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        # Requests without a "model" field in multi-model mode route to
        # the first-registered model (deterministic default) instead of
        # whichever lane the global ring happens to own.
        self.default_model: Optional[str] = None
        # Tracing: the gateway's own span ring — one ``route`` span per
        # request with ``attempt`` children (primary / retry / hedge as
        # siblings) and zero-duration ``resilience`` decision markers, so
        # every shed/retry/hedge the counters report is explainable
        # per-request in /trace/export.
        self.tracer = SpanRecorder(self.config.trace_capacity)
        # Crash-tolerant streaming + proactive lane health (DESIGN.md
        # "Crash-tolerant streaming"): stream-resume and prober decisions
        # counted here, lanes the prober ejected excluded from dispatch.
        self.failover = FailoverCounters()
        # Live stream migration (DESIGN.md "Live stream migration"):
        # per-stream KV handoff on migrate-mode drain. Decisions counted
        # here (each with a `migration` marker span); the active-stream
        # registry the drain orchestrator walks lives under self._lock.
        self.migration = MigrationCounters()
        self._streams: Dict[str, _StreamRecord] = {}
        # Disaggregated prefill/decode serving (DESIGN.md "Disaggregated
        # serving"): per-lane roles (absent = "both") drive role-aware
        # routing while config.disagg is on and the fleet is actually
        # split; every handoff decision is counted here with a matching
        # `kv_handoff` marker span. The role map lives under self._lock.
        self.handoff = HandoffCounters()
        self._roles: Dict[str, str] = {}
        # Topology-aware ring (DESIGN.md "Tensor-parallel serving" —
        # the AoiZora placement framing): per-lane mesh-shape labels
        # ({tp, mesh_shape, devices}, absent = one chip) discovered
        # from worker config (local lanes), the disagg role-discovery
        # /health read, or prober sweeps (HTTP lanes). A labelled lane
        # weights its VIRTUAL NODES by device count on every ring —
        # the ring hashes over chips, not lanes, so a TP=4 lane beside
        # TP=1 lanes draws 4x the hash share (it holds 4x the KV pool
        # at equal per-device HBM). Unlabelled fleets keep the
        # reference-exact ring byte-for-byte. Lives under self._lock.
        self._topology: Dict[str, dict] = {}
        self._topology_updates = 0  # re-weights applied (info counter)
        # Prefix-affinity routing (DESIGN.md "Prefix-affinity routing"):
        # decisions counted here; per-lane assignment totals and the
        # recent-dispatch window (imbalance signal) under self._lock.
        self.affinity = AffinityCounters()
        self._affinity_assigned: Dict[str, int] = {}
        self._lane_recent: Dict[str, object] = {}  # lane -> deque[ts]
        # Fleet prefix directory (DESIGN.md "Fleet-wide prefix tier"):
        # bounded fingerprint -> {lane, blocks, generation} hint cache
        # keyed by the SAME _affinity_fingerprint the affinity router
        # hashes — but independent of prefix_affinity (the directory
        # pays off exactly when routing CAN'T converge shared prefixes
        # onto one lane). Populated from prober /health summaries and
        # post-completion updates; entries die by per-lane generation
        # stamp on removal/drain/eject/recovery. Lives under self._lock;
        # None at defaults — /stats and wire bytes stay identical.
        self.prefix_dir = PrefixDirCounters()
        # _prefix_dir_on is the config-constant hot-path gate (set once
        # here, never reassigned); the directory itself moves only under
        # self._lock.
        self._prefix_dir_on = bool(getattr(self.config,
                                           "prefix_directory", False))
        self._prefix_dir: Optional[PrefixDirectory] = (
            PrefixDirectory(getattr(self.config,
                                    "prefix_directory_capacity", 512))
            if self._prefix_dir_on else None)
        # Adaptive overload control (DESIGN.md "Overload control"):
        # priority-tiered admission against the in-flight gauge, the
        # per-tenant token bucket, and the load-derived Retry-After.
        # Every decision counted in the additive /stats `overload` block
        # with a matching `overload` marker span.
        self.overload = OverloadCounters()
        self._tenant_bucket: Optional[TenantRateLimiter] = (
            TenantRateLimiter(self.config.tenant_rate,
                              self.config.tenant_burst)
            if self.config.tenant_rate > 0 else None)
        # In-flight requests currently inside the routing layer — the
        # gauge the tier fractions admit against (guarded by _lock).
        self._inflight = 0
        # Recent shed rate: the pressure source for load_retry_after
        # when no in-flight gauge is configured.
        self._shed_stats = SheddingStats()
        self._ejected: set = set()
        # Consistent-hash ring over the PREFILL-CAPABLE lanes (role
        # prefill|both) — the disagg primary hashes the affinity
        # fingerprint (or request_id) here so shared prefixes still
        # converge on one prefill lane. Maintained beside the main ring
        # (membership changes + role flips); ConsistentHash self-locks.
        self._prefill_ring = ConsistentHash(self.config.virtual_nodes)
        # Elastic fleet (DESIGN.md "Elastic fleet"): every autoscaler /
        # /admin/fleet decision counted here with a matching `fleet`
        # marker span. The named degraded-but-serving states (lane ->
        # reason, e.g. "spawn-wedged", "drain-wedged") and the last
        # observed fleet pressure live under self._lock; the controller
        # itself (serving/autoscaler.py) attaches via engage_autoscaler
        # and is None at defaults — wire bytes stay identical.
        self.fleet = FleetCounters()
        self._fleet_degraded: Dict[str, str] = {}
        self._fleet_pressure: Optional[float] = None
        self._autoscaler = None
        # Observability plane (DESIGN.md "Observability plane"; both
        # default off — absent, /stats and wire bytes stay identical).
        # The stream ledger records which lanes served each request_id
        # so /admin/trace/<rid> can stitch a mobile stream's fragments;
        # the SLO tracker turns the existing TTFT/ITL/completion
        # histograms into windowed error-budget burn.
        self._ledger: Optional[_StreamLedger] = (
            _StreamLedger(getattr(self.config, "trace_ledger_capacity",
                                  512))
            if getattr(self.config, "trace_stitch", False) else None)
        # Bounded lane→client handles kept past removal (drained lanes
        # stay reachable for postmortem trace stitching).
        self._retired_clients: Dict[str, object] = {}
        self._slo = SloTracker.from_config(self.config)
        self._probe_state = ProbeStateMachine(
            self.config.health_probe_failures)
        self._prober_stop = threading.Event()
        self._prober_thread: Optional[threading.Thread] = None
        for w in workers or []:
            self.add_worker(w)
        if self.config.health_probe_interval_s > 0:
            self._prober_thread = threading.Thread(
                target=self._probe_loop, name="gw-prober", daemon=True)
            self._prober_thread.start()

    def stop(self) -> None:
        """Stop the background health prober and the fleet autoscaler
        (idempotent; routing itself keeps working)."""
        scaler = self._autoscaler
        if scaler is not None:
            scaler.stop()
        self._prober_stop.set()
        t = self._prober_thread
        if t is not None:
            t.join(timeout=5)
            self._prober_thread = None

    # -- membership (elastic; reference ring was fixed at launch) ------------

    @staticmethod
    def _normalize_topology(topo) -> Optional[dict]:
        """A /health (or worker-config) topology label -> the canonical
        {tp, devices} dict, or None for unlabelled/one-chip lanes (the
        absent-key default — rings stay reference-exact)."""
        if not isinstance(topo, dict):
            return None
        try:
            devices = int(topo.get("devices", topo.get("tp", 1)))
            tp = int(topo.get("tp", devices))
        except (TypeError, ValueError):
            # Malformed labels normalize to "one chip", never raise: a
            # probe-path exception here would read as a FAILED health
            # probe and eject a perfectly healthy lane.
            return None
        if devices <= 1:
            return None
        out = {"tp": tp, "devices": devices}
        if isinstance(topo.get("mesh_shape"), dict):
            out["mesh_shape"] = dict(topo["mesh_shape"])
        return out

    def _lane_weight(self, name: str) -> int:
        """Virtual-node weight for a lane: its labelled device count
        (topology-aware ring), 1 when unlabelled."""
        with self._lock:
            topo = self._topology.get(name)
        return int(topo["devices"]) if topo else 1

    def add_worker(self, worker) -> str:
        model_name = None
        role = "both"
        topo = None
        if isinstance(worker, str):
            client = HttpWorkerClient(
                worker,
                timeout_s=self.config.worker_timeout_s,
                default_port=self.config.default_worker_port,
                gen_timeout_s=self.config.gen_timeout_s,
            )
            name = client.url
            if self.config.disagg:
                # Role (and topology) discovery for HTTP lanes (URLs
                # carry no metadata): one best-effort /health read —
                # absent keys or an unreachable lane read "both" on one
                # chip, today's behavior. Only paid when disagg is on;
                # plain HTTP fleets pick their topology labels up from
                # the health prober's sweeps instead.
                try:
                    health = client.health()
                    role = str(health.get("role", "both"))
                    topo = self._normalize_topology(
                        health.get("topology"))
                except Exception:
                    role = "both"
        else:
            client = LocalWorkerClient(worker)
            name = worker.node_id
            spec = getattr(getattr(worker, "engine", None), "spec", None)
            model_name = getattr(spec, "name", None)
            cfg = getattr(worker, "config", None)
            role = str(getattr(cfg, "role", "both") or "both")
            tp = int(getattr(cfg, "tp", 1) or 1)
            if tp > 1:
                from tpu_engine.parallel.mesh import tp_topology_label

                topo = self._normalize_topology(tp_topology_label(tp))
        if role not in ("prefill", "decode", "both"):
            role = "both"
        weight = int(topo["devices"]) if topo else 1
        with self._lock:
            self._clients[name] = client
            self._breakers[name] = self._make_breaker()
            if role != "both":
                self._roles[name] = role
            if topo is not None:
                self._topology[name] = topo
            if model_name is None:
                self._untyped.add(name)
        self._ring.add_node(name, weight)
        if role != "decode":
            self._prefill_ring.add_node(name, weight)
        if model_name is not None:
            with self._lock:
                ring = self._model_rings.get(model_name)
                if ring is None:
                    # Populate BEFORE publishing: a concurrent _route must
                    # never see an empty ring for a registered model.
                    ring = ConsistentHash(self.config.virtual_nodes)
                    ring.add_node(name, weight)
                    self._model_rings[model_name] = ring
                else:
                    ring.add_node(name, weight)
                if self.default_model is None:
                    self.default_model = model_name
        return name

    def _apply_topology(self, name: str, topo) -> None:
        """Adopt a lane's freshly-discovered topology label (prober
        sweeps: an HTTP lane's /health is the only place its mesh shape
        exists) and re-weight its virtual nodes on every ring it is a
        member of. No-op while the label is unchanged — steady-state
        sweeps touch nothing."""
        topo = self._normalize_topology(topo)
        with self._lock:
            if name not in self._clients:
                return
            prev = self._topology.get(name)
            if topo == prev:
                return
            if topo is None:
                self._topology.pop(name, None)
            else:
                self._topology[name] = topo
            rings = list(self._model_rings.values())
        weight = int(topo["devices"]) if topo else 1
        # ConsistentHash self-locks; resize outside the gateway lock.
        # reweight_node is atomic (membership check + resize under one
        # ring-lock acquisition), so a remove_worker racing this sweep
        # can never be interleaved into a resurrected ghost lane — the
        # resize simply misses (False) once the removal lands.
        applied = self._ring.reweight_node(name, weight)
        self._prefill_ring.reweight_node(name, weight)
        for ring in rings:
            ring.reweight_node(name, weight)
        if applied:
            with self._lock:
                if name in self._clients:
                    self._topology_updates += 1
                else:
                    self._topology.pop(name, None)

    def _make_breaker(self):
        """Native breaker when the C++ core is loaded — the native HTTP
        front shares the same breaker object for its hit-path gate."""
        try:
            from tpu_engine.core import native

            if native.available():
                return native.NativeCircuitBreaker(
                    self.config.failure_threshold,
                    self.config.success_threshold,
                    self.config.breaker_timeout_s,
                )
        except Exception:
            pass
        return CircuitBreaker(
            self.config.failure_threshold,
            self.config.success_threshold,
            self.config.breaker_timeout_s,
        )

    def breaker_for(self, name: str):
        with self._lock:
            return self._breakers.get(name)

    # -- proactive lane health (prober) ---------------------------------------

    def _probe_loop(self) -> None:
        """Background prober: GET every lane's /health each interval;
        `health_probe_failures` consecutive failures eject the lane from
        dispatch (no breaker penalty — ejection is reversible and
        fleet-wide in one sweep), the next success restores it. Catches a
        dead or wedged worker in O(probe interval) instead of one
        breaker trip per victim request."""
        interval = self.config.health_probe_interval_s
        while not self._prober_stop.wait(interval):
            with self._lock:
                clients = dict(self._clients)
            for name, client in clients.items():
                ok = False
                try:
                    # Dedicated probe connection where the client offers
                    # one (HTTP lanes): probes must never contend with
                    # data traffic for pool slots.
                    probe = getattr(client, "probe_health", client.health)
                    body = probe()
                    ok = bool(body.get("healthy", False))
                    # Topology labels ride the same read: an HTTP lane's
                    # mesh shape exists nowhere but its /health, so the
                    # prober is where TP=4 lanes pick up their per-chip
                    # vnode weight (no-op while the label is unchanged).
                    self._apply_topology(name, body.get("topology"))
                    # Directory seeding rides the same read: the lane's
                    # bounded top-K radix summaries (present only with
                    # --prefix-fetch on worker-side) become fleet-wide
                    # fingerprint->owner entries.
                    if self._prefix_dir_on:
                        self._seed_prefix_dir(
                            name, body.get("prefix_fingerprints"))
                except Exception:
                    ok = False  # unreachable = failed probe
                action = self._probe_state.record(name, ok)
                with self._lock:
                    present = name in self._clients
                if not present:
                    # Removed while this sweep held the stale snapshot:
                    # record() just resurrected its state — drop it again
                    # so a later lane reusing the name starts clean (and
                    # unique elastic lane names don't leak entries).
                    self._probe_state.forget(name)
                    continue
                if action is None:
                    continue
                with self._lock:
                    if name not in self._clients:
                        continue  # removed between the checks
                    if action == "eject":
                        self._ejected.add(name)
                    else:
                        self._ejected.discard(name)
                self.failover.bump("prober_ejections" if action == "eject"
                                   else "prober_restores")
                self._prober_span(name, action)
                # Both transitions void the lane's directory entries: an
                # ejected lane can't serve a peer fetch, and a RECOVERED
                # lane may have restarted with an empty radix tree — its
                # chains must be re-learned, not assumed.
                if self._prefix_dir_on:
                    with self._lock:
                        dropped = self._prefix_dir.invalidate_lane(name)
                    self._prefix_dir_count("invalidations", lane=name,
                                           action=action, dropped=dropped)

    def _prober_span(self, lane: str, action: str) -> None:
        """Zero-duration ``prober`` marker span per eject/restore — the
        counters say how often, the spans say WHICH lane and when
        (fault_injection --crash asserts the two agree)."""
        ctx = TraceContext.root(f"prober:{lane}").child()
        self.tracer.record(
            "prober", "prober", "gateway", 0,
            trace_id=ctx.trace_id, span_id=ctx.span_id,
            start_ts=time.time(), attrs={"lane": lane, "action": action})

    def ejected_lanes(self) -> List[str]:
        with self._lock:
            return sorted(self._ejected)

    def remove_worker(self, name: str, drain: bool = False) -> None:
        """Remove a lane from every ring. ``drain=True`` = graceful
        (lame-duck) removal: the lane refuses NEW admissions first — so a
        request racing the ring update sheds with 503 instead of failing —
        while in-flight work runs to completion off-ring. The drain call
        is BOUNDED (``drain_timeout_s``): a wedged lane's acknowledgment
        must never hang a membership change — the failure is counted
        (``drain_failures``) and removal proceeds. With
        ``migrate_streams`` on, every journaled in-flight stream on the
        lane is then EXPORTED and resumed mid-stream elsewhere (zero
        re-prefilled tokens) before the lane leaves the rings; any
        per-stream failure falls back to the replay resume. The default
        stays the abrupt removal existing callers expect."""
        if drain:
            with self._lock:
                client = self._clients.get(name)
            if client is not None and hasattr(client, "drain"):
                fut = self._pool().submit(client.drain)
                try:
                    fut.result(timeout=self.config.drain_timeout_s)
                except Exception as exc:
                    # Wedged or unreachable lane: count it, drop the
                    # marker span, and carry on — plain removal is all
                    # we have (the abandoned call finishes or dies on
                    # its pool thread).
                    self._migration_count(None, "drain_failures",
                                          lane=name,
                                          error=str(exc)[:120])
            if self.config.migrate_streams:
                self._migrate_lane_streams(name, client)
        self._ring.remove_node(name)
        self._prefill_ring.remove_node(name)
        with self._lock:
            rings = dict(self._model_rings)
            removed_client = self._clients.pop(name, None)
            if self._ledger is not None and removed_client is not None:
                # The stitcher may still need this lane's span fragments
                # (a drained lane is alive, just not a member): keep a
                # BOUNDED handle so /admin/trace can reach it postmortem.
                self._retired_clients[name] = removed_client
                while len(self._retired_clients) > 8:
                    self._retired_clients.pop(
                        next(iter(self._retired_clients)))
            self._breakers.pop(name, None)
            self._latency.pop(name, None)  # stale window must not feed thresholds
            self._lane_recent.pop(name, None)
            self._untyped.discard(name)
            self._ejected.discard(name)
            self._roles.pop(name, None)
            self._topology.pop(name, None)
            # Generation-stamp invalidation: the departing lane's radix
            # tree leaves the fleet with it — every directory entry
            # naming it is a dead hint (a later lane reusing the name
            # starts at a fresh generation, so stragglers die lazily).
            pd_dropped = (self._prefix_dir.invalidate_lane(name)
                          if self._prefix_dir is not None else None)
        if pd_dropped is not None:
            self._prefix_dir_count("invalidations", lane=name,
                                   action="remove", dropped=pd_dropped)
        # A later lane reusing the name must start with clean probe state.
        self._probe_state.forget(name)
        for ring in rings.values():
            ring.remove_node(name)
        with self._lock:
            # Prune emptied sub-rings and re-point the no-field default —
            # removing the default model's last lane must not strand every
            # field-less request on a dead ring forever.
            for mdl, ring in list(self._model_rings.items()):
                if not ring.get_all_nodes():
                    del self._model_rings[mdl]
            if self.default_model not in self._model_rings:
                self.default_model = (sorted(self._model_rings)[0]
                                      if self._model_rings else None)

    def worker_names(self) -> List[str]:
        return self._ring.get_all_nodes()

    # -- elastic fleet (DESIGN.md "Elastic fleet") ----------------------------

    def lane_clients(self) -> Dict[str, object]:
        """{lane: client} membership snapshot (one lock acquisition) —
        the autoscaler's observation loop and tests."""
        with self._lock:
            return dict(self._clients)

    def _fleet_count(self, decision: str, **attrs) -> None:
        """Bump a fleet counter AND drop a zero-duration ``fleet``
        marker span (same counters==spans discipline as the
        migration/handoff markers; fault_injection --elastic asserts
        the two agree)."""
        self.fleet.bump(decision)
        ctx = TraceContext.root(f"fleet:{decision}").child()
        self.tracer.record(
            "fleet", "fleet", "gateway", 0,
            trace_id=ctx.trace_id, span_id=ctx.span_id,
            start_ts=time.time(), attrs={"decision": decision, **attrs})

    def fleet_observe(self, pressure: float) -> None:
        """Publish the controller's latest fleet-pressure observation
        (drives the /stats ``fleet.pressure`` gauge)."""
        with self._lock:
            self._fleet_pressure = round(float(pressure), 4)

    def fleet_enter_degraded(self, lane: str, reason: str) -> None:
        """Latch a NAMED degraded-but-serving state for ``lane``
        (``spawn-wedged``: a scale-up that never turned healthy;
        ``drain-wedged``: a scale-down whose drain leg wedged or whose
        actuator timed out). Serving continues unchanged — the state is
        an operator signal, visible in /stats ``fleet`` and
        /admin/fleet until cleared. Idempotent per (lane, reason)."""
        with self._lock:
            if self._fleet_degraded.get(lane) == reason:
                return
            self._fleet_degraded[lane] = reason
        self._fleet_count("degraded_entered", lane=lane, reason=reason)
        # Flight-recorder anomaly hook: entering a degraded fleet state
        # is exactly the moment an operator wants the last N ticks of
        # every lane on disk. Best-effort — lanes without a recorder
        # (or unreachable ones) simply skip.
        for name, client in self.lane_clients().items():
            if hasattr(client, "flight_dump"):
                try:
                    client.flight_dump(f"fleet_degraded:{reason}")
                except Exception:
                    pass

    def fleet_clear_degraded(self, lane: str) -> bool:
        """Clear a lane's degraded state (controller recovery sweep or
        operator /admin/fleet clear). True if a state was latched."""
        with self._lock:
            reason = self._fleet_degraded.pop(lane, None)
        if reason is None:
            return False
        self._fleet_count("degraded_cleared", lane=lane, reason=reason)
        return True

    def fleet_status(self) -> dict:
        """The /admin/fleet status body: membership, named degraded
        states, controller engagement, and last observed pressure."""
        with self._lock:
            degraded = dict(self._fleet_degraded)
            pressure = self._fleet_pressure
        lanes = self.worker_names()
        out = {
            "state": ("degraded:" + ",".join(sorted(set(degraded.values())))
                      if degraded else "steady"),
            "lanes": sorted(lanes),
            "degraded": degraded,
            "autoscale": self._autoscaler is not None
            and self._autoscaler.running,
        }
        if pressure is not None:
            out["pressure"] = pressure
        return out

    # -- observability plane (DESIGN.md "Observability plane") ---------------

    def slo_status(self, named_hists: Optional[dict] = None) -> Optional[dict]:
        """The /admin/slo payload, or None when no objective is
        configured. ``named_hists`` is the combined front's merged
        ``{family: {node: hist}}`` map; without it the gateway gathers
        what it can reach directly — in-process lanes expose their live
        ``latency_histograms()``, remote (HTTP) lanes contribute nothing
        (their TTFT/ITL windows live behind /metrics text, not live
        objects; the completion objective still covers them because it
        reads the GATEWAY's own request-level histograms)."""
        if self._slo is None:
            return None
        if named_hists is None:
            named_hists = {}
            for lane, client in self.lane_clients().items():
                w = getattr(client, "worker", None)
                if w is None or not hasattr(w, "latency_histograms"):
                    continue
                for name, by_node in w.latency_histograms().items():
                    named_hists.setdefault(name, {}).update(by_node)
        by_objective = {}
        for name, family in OBJECTIVE_SOURCES.items():
            if family is None:
                # "completion" = the gateway's own generate-op spans:
                # full client-visible latency including failover,
                # handoff, and migration time.
                by_objective[name] = completion_hists([self.tracer])
            else:
                by_objective[name] = list(
                    (named_hists.get(family) or {}).values())
        return self._slo.status(by_objective)

    def slo_pressure(self, named_hists: Optional[dict] = None) -> float:
        """The autoscaler feed: worst objective burn mapped to [0, 1]
        (0.0 with no tracker — the feed is strictly additive)."""
        if self._slo is None:
            return 0.0
        status = self.slo_status(named_hists)
        return SloTracker.pressure(status or {})

    def stitched_trace(self, request_id: str,
                       fragments: Optional[dict] = None) -> dict:
        """The /admin/trace/<request_id> body: every lane's span
        fragments for one (possibly thrice-moved) stream merged into a
        single tree. The stream ledger supplies the trace_id and the
        hop history when stitching is on; without a ledger entry (plain
        deployments, evicted entries) the stitch still works from the
        request_id + derived trace_id — the ledger is an index, not the
        data. Lane fragment collection is best-effort: a dead lane
        contributes nothing rather than failing the whole stitch (its
        spans died with it; the synthetic ``evicted_parent`` roots keep
        the surviving tree connected)."""
        entry = (self._ledger.get(request_id)
                 if self._ledger is not None else None)
        if fragments is None:
            fragments = {"gateway": self.tracer.snapshot()}
            for lane, client in self.lane_clients().items():
                if not hasattr(client, "trace_spans"):
                    continue
                try:
                    spans = client.trace_spans()
                except Exception:
                    continue
                if spans:
                    fragments.setdefault(lane, spans)
            # A drained lane is alive but no longer a ring member — the
            # ledger remembers it served this stream, so chase its
            # fragments through the retired-client handle (kept by
            # remove_worker) or a fresh HTTP probe (best-effort: a
            # KILLED lane's spans died with it and simply fail here).
            with self._lock:
                retired = dict(self._retired_clients)
            for hop in (entry or {}).get("hops", ()):
                lane = hop.get("lane") or ""
                if not lane or lane in fragments:
                    continue
                client = retired.get(lane)
                if client is None and ":" in lane:
                    try:
                        from tpu_engine.serving.clients import (
                            HttpWorkerClient,
                        )

                        client = HttpWorkerClient(lane, timeout_s=3.0)
                    except Exception:
                        continue
                if client is None or not hasattr(client, "trace_spans"):
                    continue
                try:
                    spans = client.trace_spans()
                except Exception:
                    continue
                if spans:
                    fragments.setdefault(lane, spans)
        out = stitch_trace(fragments, request_id,
                           trace_id=(entry or {}).get("trace_id"))
        if entry is not None:
            out["hops"] = entry["hops"]
        return out

    def engage_autoscaler(self, provider=None):
        """Create (and start) the closed-loop fleet controller —
        called by the serving app when ``--autoscale`` is set. Returns
        the controller; idempotent (a live controller is reused)."""
        if self._autoscaler is None:
            from tpu_engine.serving.autoscaler import FleetAutoscaler

            self._autoscaler = FleetAutoscaler(self, provider=provider,
                                               config=self.config)
        if self.config.autoscale:
            self._autoscaler.start()
        return self._autoscaler

    def _fleet_controller(self):
        """The controller backing /admin/fleet: the engaged autoscaler,
        or an UNSTARTED one (manual actuations share the exact probe /
        drain+migrate ladders, counters, and degraded-state handling
        the closed loop uses — defaults-off deployments get the same
        semantics without any background thread)."""
        if self._autoscaler is None:
            from tpu_engine.serving.autoscaler import FleetAutoscaler

            self._autoscaler = FleetAutoscaler(self, provider=None,
                                               config=self.config)
        return self._autoscaler

    def fleet_admin(self, payload: dict) -> dict:
        """/admin/fleet: the elastic-fleet operator surface. Actions —
        ``status`` (fleet + controller state), ``add`` (probe-then-
        register a lane: a worker address, registered on the rings only
        after a passing /health probe), ``remove`` (retire a member
        through the drain + PR 11 stream-migration ladder), ``rebalance``
        (flip a lane's role through the /admin/role path), ``clear``
        (drop a lane's latched degraded state). Every failure answers a
        named, non-raising status."""
        action = str(payload.get("action", "status"))
        ctl = self._fleet_controller()
        if action == "status":
            out = {"ok": True, **self.fleet_status()}
            out["counters"] = self.fleet.as_dict()
            return out
        if action == "add":
            worker = payload.get("worker")
            if not worker:
                return {"ok": False, "status": "missing-worker"}
            return ctl.scale_up(worker=worker)
        if action == "remove":
            name = payload.get("worker")
            if not name:
                return {"ok": False, "status": "missing-worker"}
            return ctl.scale_down(name=str(name), manual=True)
        if action == "rebalance":
            name, role = payload.get("worker"), payload.get("role")
            if not name or not role:
                return {"ok": False, "status": "missing-worker-or-role"}
            return ctl.rebalance(str(name), str(role))
        if action == "clear":
            name = payload.get("worker")
            if not name:
                return {"ok": False, "status": "missing-worker"}
            cleared = self.fleet_clear_degraded(str(name))
            return {"ok": True,
                    "status": "cleared" if cleared else "not-degraded"}
        return {"ok": False, "status": f"unknown-action:{action}"[:80]}

    # -- request path ---------------------------------------------------------

    def route_request(self, payload: dict) -> dict:
        return self._route(payload, op="infer")

    def route_request_raw(self, payload: dict) -> bytes:
        """Hot path: response stays pre-serialized bytes end-to-end (the
        reference re-parses and re-encodes the float array at every hop)."""
        return self._route(payload, op="infer_raw")

    def route_score(self, payload: dict) -> dict:
        """Route /score (teacher-forced logprobs) like /infer."""
        return self._route(payload, op="score")

    def route_generate(self, payload: dict) -> dict:
        """Route a /generate request the same way as /infer: ring primary,
        breaker-gated, ring-order failover. Under active
        disaggregation the blocking call rides the same prefill→decode
        handoff path as the stream (collapsed into the blocking
        response)."""
        if self._disagg_split() is not None:
            return self._generate_via_handoff(payload)
        return self._route(payload, op="generate")

    def route_generate_stream(self, payload: dict):
        """Streaming variant: same routing; the selected lane's SSE
        event-chunk iterator is handed back for chunked transfer.
        Breaker accounting happens at admission (iterator creation) AND
        on mid-stream lane faults (below).

        Default (``failover_streams`` off): a mid-stream failure still
        ends the client's stream (error event or truncation — same
        frames, same wire behavior as before), but the dying lane's
        breaker now records the fault, preserving the breaker signal the
        old buffering HTTP shim got for free at iterator creation. With
        ``failover_streams`` on, the gateway journals every token event
        it relays and a retryable mid-stream failure RESUMES the stream
        on another ring lane (prompt ⧺ emitted tokens as a forced
        prefix), splicing the continuation so the client sees one
        seamless, byte-identical stream — the request is bound to the
        fleet, not to the lane that happened to start it."""
        if not (self.config.failover_streams
                or self.config.migrate_streams
                or self.config.disagg):
            info: dict = {}
            it = self._route(payload, op="generate_stream",
                             out_info=info)
            return self._breaker_watched(it, info.get("lane"))
        # migrate_streams implies the journal: the replay resume IS the
        # migration fallback ladder's last rung (MIGRATION.md). Disagg
        # needs the journal for the same reason — the handoff's last
        # rung is the replay resume.
        return self._stream_with_failover(payload)

    def _breaker_watched(self, it, lane: Optional[str]):
        """Relay a stream iterator byte-identically, but feed a
        mid-stream LANE fault to the lane's breaker — admission-time
        accounting alone would let a lane that admits streams and then
        dies stay CLOSED forever. Two fault shapes: a mid-iteration
        exception (transport death), and a worker-side in-band terminal
        error EVENT marked retryable (device fault re-framed as SSE —
        the shape the old buffering HTTP shim surfaced as a WorkerError
        at dispatch). Request-fault and shed signals pass through
        unpenalized (`shed` marker / exception class), the same
        classification `_try_node` applies at admission."""
        def watched():
            try:
                for frame in it:
                    # Cheap prefilter keeps the per-token hot path at
                    # relay cost: only terminal frames carry "done".
                    if b'"done"' in frame:
                        evt = _parse_sse(frame)
                        if (evt is not None and evt.get("done")
                                and "error" in evt
                                and evt.get("retryable")
                                and not evt.get("shed")):
                            self._stream_fault_penalty(lane)
                    yield frame
            except (KeyError, ValueError, TypeError):
                raise
            except ShedError as exc:
                if getattr(exc, "lane_suspect", False):
                    self._stream_fault_penalty(lane)  # hang signature
                raise
            except Exception:
                self._stream_fault_penalty(lane)
                raise
        return watched()

    def _stream_fault_penalty(self, lane: Optional[str]) -> None:
        breaker = self.breaker_for(lane) if lane else None
        if breaker is not None:
            breaker.record_failure()

    def _resume_payload(self, payload: dict, emitted: List[int],
                        max_new: int,
                        deadline: Optional[Deadline]) -> dict:
        """The resume request: the original payload with the emitted
        tokens appended to the prompt as a forced prefix and the token
        budget offset by the emitted count. Determinism across the
        splice boundary needs no extra wire fields: the scheduler
        samples with fold_in(seed, absolute position) and replays
        penalty counts / stop matching from the (prompt ⧺ emitted)
        prefix at admission, so greedy AND seeded sampled continuations
        are byte-identical to an uninterrupted run (tests/test_failover
        pins this; MIGRATION.md documents the positional-fold
        requirement)."""
        prompt = [int(t) for t in payload.get("prompt_tokens", ())]
        resume = {**payload,
                  "prompt_tokens": prompt + list(emitted),
                  "max_new_tokens": max_new - len(emitted)}
        if deadline is not None:
            # Forward the budget REMAINING now — a resume must never
            # restart the client's clock.
            resume["deadline_ms"] = max(0.0, deadline.remaining_ms())
        return resume

    def _resume_span(self, request_id: str, ctx, index: int,
                     replayed: int, outcome: str,
                     lane: Optional[str]) -> None:
        """One ``resume`` span per resume attempt, parented under the
        request's trace — resumes_attempted and these spans must agree
        (fault_injection --crash asserts it)."""
        child = ctx.child()
        self.tracer.record(
            request_id, "resume", "gateway", 0,
            trace_id=child.trace_id, span_id=child.span_id,
            parent_id=ctx.span_id, start_ts=time.time(),
            attrs={"resume": index, "tokens_replayed": replayed,
                   "outcome": outcome, "lane": lane or "?"})

    def _stream_with_failover(self, payload: dict):
        """Crash-tolerant /generate/stream: the journal is the request
        payload plus every token relayed so far; a retryable mid-stream
        failure (transport death, truncated stream, a worker error event
        marked retryable, a drain shed) re-dispatches to the next ring
        lane as a resume — consuming the PR 1 retry budget and the
        request's original deadline — and the continuation is spliced in
        with no duplicated or missing tokens. Non-resumable ends (budget
        exhausted, deadline expired, all lanes down, resume cap) emit a
        terminal error event carrying ``retryable``, ``trace_id``, and
        ``tokens_emitted`` so the CLIENT can resume manually."""
        rid = payload.get("request_id")
        if rid is None:
            rid = uuid.uuid4().hex
            payload = {**payload, "request_id": rid}
        request_id = str(rid)
        # Pin the deadline ONCE: every resume forwards what remains.
        deadline = Deadline.from_request(
            payload, default_ms=self.config.default_deadline_ms)
        try:
            max_new = int(payload.get("max_new_tokens", 32))
        except (TypeError, ValueError):
            # Malformed budget: let the normal path 400 it.
            return self._route(payload, op="generate_stream")
        parent = TraceContext.from_request(payload)
        ctx = (parent.child() if parent is not None
               else TraceContext.root(request_id))
        cfg = self.config
        ledger = self._ledger
        t_root = time.time()
        if ledger is not None:
            # Cross-lane trace stitching (--trace-stitch): forward the
            # STREAM-ROOT context in the payload once — the first
            # dispatch, every replay resume (_resume_payload copies the
            # payload), and both mobility continuations (built from
            # record.payload) inherit it, so each segment's
            # route/attempt/worker spans join ONE tree under the root
            # span recorded at stream end. Off (default), traceless
            # payloads keep their wire bytes byte-identical.
            payload = {**payload, "traceparent": ctx.to_traceparent()}
        # Disaggregated serving: while the fleet is split, the FIRST
        # segment is stamped `handoff` — routed to a prefill-capable
        # lane which parks the row after prefill for the
        # export-after-prefill command. The record keeps the UNSTAMPED
        # payload: resumes and continuations must never re-park.
        disagg = self._disagg_split() is not None
        dispatch_payload = payload
        if disagg:
            dispatch_payload = {
                **payload, "handoff": True,
                "handoff_park_ms": cfg.handoff_timeout_s * 1000.0}
        info: dict = {}
        # Admission of the FIRST segment keeps every existing semantic:
        # shed/400/no-workers raise here, before the 200 SSE commits.
        first = self._route(dispatch_payload, op="generate_stream",
                            out_info=info)
        if ledger is not None:
            ledger.hop(request_id, info.get("lane") or "?", "admit",
                       ctx.trace_id)
        # Migrate mode (and disagg — the handoff rides the same relay):
        # register the stream so the orchestrator can find it (which
        # lane serves it, its payload and deadline) and hand the relay
        # a continuation. Registered only AFTER the first segment
        # admitted — a stream that never started has nothing to
        # migrate.
        record: Optional[_StreamRecord] = None
        if cfg.migrate_streams or disagg:
            record = _StreamRecord(request_id, payload, deadline, ctx,
                                   info.get("lane"))
            with self._lock:
                self._streams[request_id] = record
        if disagg and record is not None:
            lane = info.get("lane")
            with self._lock:
                lane_role = self._roles.get(lane, "both")
            if lane_role == "prefill":
                # The steady-state handoff orchestrator owns this
                # stream's prefill→decode hop from here (one handoff-
                # pool thread per stream, bounded by handoff_timeout_s).
                self._handoff_pool().submit(self._handoff_stream,
                                            record, lane)
            else:
                # The stamped stream landed COLOCATED — ring fallback
                # past the prefill lanes, or a model ring with no split
                # (disagg activation is fleet-wide; this request's ring
                # may not be). A both/decode lane decodes fine itself:
                # no pointless KV transfer — just release the park so
                # the row never waits out a window nobody will collect
                # (the cancel pre-empts a row that has not parked yet).
                self._handoff_pool().submit(self._cancel_colocated_hold,
                                            record, lane)

        def terminal_error(reason: str, retryable: bool,
                           emitted: List[int]) -> bytes:
            return sse_event({
                "done": True, "error": str(reason)[:300],
                "retryable": bool(retryable),
                "request_id": request_id, "trace_id": ctx.trace_id,
                "tokens_emitted": len(emitted),
                "tokens": list(emitted)})

        def spliced_inner():
            emitted: List[int] = []
            it = first
            lane = info.get("lane")
            resumes = 0
            while True:
                # failure: (reason, retryable, lane_fault) — lane_fault
                # feeds the lane's breaker; sheds and client-budget
                # expiries don't (the healthy-lane rule).
                failure: Optional[tuple] = None
                finished = False
                migrated_evt = False
                try:
                    try:
                        for frame in it:
                            evt = _parse_sse(frame)
                            if evt is None:
                                yield frame  # not ours to interpret
                                continue
                            if not evt.get("done"):
                                toks = evt.get("tokens")
                                if isinstance(toks, list):
                                    # Materialize BEFORE extending: a
                                    # malformed token raising mid-extend
                                    # would leave the journal holding
                                    # tokens of a frame the client never
                                    # received, and the resume would
                                    # splice past them.
                                    emitted.extend([int(t) for t in toks])
                                yield frame
                                continue
                            if "error" in evt:
                                # Worker-side terminal error: its own
                                # `retryable` classification decides
                                # (absent = not retryable — never resume
                                # blind); a `shed` marker means a HEALTHY
                                # lane refused (drain/overload) — resume
                                # without a breaker penalty. A `migrated`
                                # marker means the row was EXPORTED: the
                                # drain orchestrator is (or was) moving
                                # it — await the handoff below instead
                                # of replay-resuming blind.
                                retr = bool(evt.get("retryable", False))
                                migrated_evt = bool(evt.get("migrated"))
                                if (evt.get("import_refused")
                                        and record is not None):
                                    # The spliced continuation's import
                                    # was refused post-dispatch
                                    # (checksum / geometry / pool
                                    # pressure): attribute the replay
                                    # fallback to the MIGRATION — or to
                                    # the HANDOFF when the latest
                                    # splice was the steady-state hop —
                                    # the destination lane is healthy.
                                    if record.spliced_handoff:
                                        self._handoff_count(
                                            "handoff_fallbacks",
                                            record=record, lane=lane,
                                            cause="import_refused")
                                    else:
                                        self._migration_count(
                                            record, "migration_fallbacks",
                                            lane=lane,
                                            cause="import_refused")
                                failure = (str(evt.get("error")), retr,
                                           retr
                                           and not evt.get("shed", False)
                                           and not migrated_evt
                                           and not evt.get(
                                               "import_refused", False))
                            else:
                                # Clean terminal: rewrite the summary to
                                # the FULL spliced stream (a resumed
                                # segment's summary covers only its
                                # continuation).
                                done = {**evt, "request_id": request_id,
                                        "tokens": list(emitted)}
                                if resumes:
                                    done["resumed"] = resumes
                                yield sse_event(done)
                                finished = True
                            break
                        else:
                            # Iterator exhausted without a terminal
                            # event: the lane died between frames
                            # (kill -9 closes the socket mid-chunk) —
                            # resumable truncation.
                            failure = ("stream truncated mid-generation",
                                       True, True)
                    finally:
                        # Settle the segment iterator NOW, not at GC: a
                        # finished HTTP segment reads one step past the
                        # done event so its pooled connection releases
                        # clean; every other exit closes it promptly
                        # (dead conns must never wait for a collector).
                        if finished:
                            try:
                                next(it)
                            except StopIteration:
                                pass
                            except Exception:
                                pass
                        try:
                            it.close()
                        except Exception:
                            pass
                except DeadlineExceeded as exc:
                    # Budget spent: terminal. No lane penalty UNLESS the
                    # lane held the request past the budget without
                    # answering (lane_suspect — the hang signature, same
                    # rule _route applies at admission).
                    failure = (str(exc), False,
                               bool(getattr(exc, "lane_suspect", False)))
                except ShedError as exc:
                    failure = (str(exc), True, False)  # drain: move on
                except Exception as exc:
                    failure = (str(exc), True, True)   # transport fault
                if finished:
                    return
                reason, retryable, lane_fault = failure
                if migrated_evt and record is not None:
                    # The row was EXPORTED off its lane: await the drain
                    # orchestrator's continuation (bounded by the
                    # transfer budget AND the stream's original
                    # deadline) and splice it — the client sees one
                    # seamless stream with zero re-prefilled tokens.
                    # Any failure — export refused, destination full or
                    # dead, checksum mismatch, timeout — falls through
                    # to the replay resume below: the fallback ladder's
                    # last rung needs nothing from either side.
                    is_handoff = record.handoff
                    wait_s = (cfg.handoff_timeout_s if is_handoff
                              else cfg.migrate_timeout_s) + 5.0
                    if deadline is not None:
                        wait_s = min(wait_s,
                                     max(0.0, deadline.remaining_s()))
                    handoff = record.await_handoff(wait_s)
                    if handoff is not None:
                        it, new_lane = handoff
                        lane = new_lane
                        record.lane = new_lane
                        record.spliced_handoff = is_handoff
                        if ledger is not None:
                            ledger.hop(request_id, new_lane or "?",
                                       "handoff" if is_handoff
                                       else "migrate", ctx.trace_id)
                        if is_handoff:
                            # The steady-state prefill→decode hop
                            # landed: the decode lane adopted the chain
                            # with zero re-prefilled tokens.
                            record.handoff = False
                            self._handoff_count("handoffs_spliced",
                                                record=record,
                                                lane=new_lane)
                            self.handoff.bump("tokens_handed_off",
                                              len(emitted))
                        else:
                            self._migration_count(record,
                                                  "streams_migrated",
                                                  lane=new_lane)
                            self.migration.bump("tokens_migrated",
                                                len(emitted))
                        continue
                    if is_handoff:
                        record.handoff = False
                        self._handoff_count("handoff_fallbacks",
                                            record=record, lane=lane)
                        reason = (f"handoff fell back to replay "
                                  f"({reason})")
                    else:
                        self._migration_count(record,
                                              "migration_fallbacks",
                                              lane=lane)
                        reason = (f"migration fell back to replay "
                                  f"({reason})")
                    retryable = True
                self.failover.bump("stream_failures")
                if lane_fault:
                    # Admission recorded a breaker SUCCESS for this lane;
                    # without this, a lane that admits streams and then
                    # dies mid-generation would stay CLOSED forever.
                    self._stream_fault_penalty(lane)
                if len(emitted) >= max_new > 0:
                    # The budget was fully delivered; only the terminal
                    # frame was lost. Synthesize it — nothing to resume.
                    done = {"done": True, "request_id": request_id,
                            "tokens": list(emitted)}
                    if resumes:
                        done["resumed"] = resumes
                    yield sse_event(done)
                    return
                if not retryable:
                    yield terminal_error(reason, False, emitted)
                    return
                if deadline is not None and deadline.expired():
                    self._count(None, "deadline_expired")
                    yield terminal_error(
                        f"deadline exceeded after mid-stream failure "
                        f"({reason})", False, emitted)
                    return
                if resumes >= cfg.failover_max_resumes:
                    yield terminal_error(
                        f"stream failed after {resumes} resumes "
                        f"({reason})", True, emitted)
                    return
                # Budget accounting rides the resume DISPATCH below, not a
                # separate pre-draw: the dead lane is (almost always) the
                # rid's ring primary, so the skip-path failover march
                # charges the global retry budget one token per alternate
                # lane tried — a resume costs exactly what any other
                # extra dispatch costs, and budget exhaustion surfaces
                # from _route as the terminal error.
                resumes += 1
                replayed = len(emitted)
                self.failover.bump("resumes_attempted")
                self.failover.bump("tokens_replayed", replayed)
                resume = self._resume_payload(payload, emitted, max_new,
                                              deadline)
                skip = (lane,) if lane else ()
                nxt_info: dict = {}
                try:
                    it = self._route(resume, op="generate_stream",
                                     skip=skip, out_info=nxt_info)
                except Exception as exc:
                    # No lane could admit the resume (all down, all
                    # shedding, or the deadline died en route).
                    self.failover.bump("resumes_failed")
                    self._resume_span(request_id, ctx, resumes, replayed,
                                      "failed", lane)
                    yield terminal_error(
                        f"resume dispatch failed ({exc})",
                        not isinstance(exc, DeadlineExceeded), emitted)
                    return
                self.failover.bump("resumes_succeeded")
                lane = nxt_info.get("lane")
                self._resume_span(request_id, ctx, resumes, replayed,
                                  "ok", lane)
                if ledger is not None:
                    ledger.hop(request_id, lane or "?", "resume",
                               ctx.trace_id)
                # A lane death IS an anomaly: ask the resume lane's
                # flight recorder for a postmortem dump named for the
                # event (no-op on lanes without the recorder armed) —
                # the black box fault_injection --stitch checks after
                # its kill -9.
                resume_client = self.lane_clients().get(lane or "")
                if resume_client is not None and hasattr(
                        resume_client, "flight_dump"):
                    try:
                        resume_client.flight_dump(
                            f"failover_resume:{request_id}")
                    except Exception:
                        pass
                if record is not None:
                    # The replay segment owns the stream now: a LATER
                    # migrate-mode drain of its lane must find it, and
                    # a stale abandoned handoff must not refuse it.
                    record.lane = lane
                    record.rearm()

        def spliced():
            try:
                yield from spliced_inner()
            finally:
                if ledger is not None:
                    # The STREAM-ROOT span, recorded at stream end with
                    # span_id == ctx.span_id: every hop marker
                    # (migration / kv_handoff / resume) and each
                    # segment's route span parent here, so the stitched
                    # tree is orphan-free by construction — the exact
                    # property fault_injection --stitch asserts.
                    self.tracer.record(
                        request_id, "stream", "gateway",
                        (time.time() - t_root) * 1e6,
                        trace_id=ctx.trace_id, span_id=ctx.span_id,
                        parent_id=(parent.span_id
                                   if parent is not None else None),
                        start_ts=t_root, attrs={"stitched": True})
                if record is not None:
                    with self._lock:
                        if self._streams.get(request_id) is record:
                            del self._streams[request_id]
                    # An offered continuation the relay never consumed
                    # (the stream ended another way — e.g. the source's
                    # terminal frames were lost to a kill before the
                    # migrated marker arrived): dispose of it, or the
                    # destination's admission depth stays pinned.
                    orphan = record.take_unconsumed()
                    if orphan is not None:
                        self._dispose_iter(orphan)
        return spliced()

    # -- live stream migration (DESIGN.md "Live stream migration") ------------

    def _migration_count(self, record: Optional[_StreamRecord],
                         decision: str, **attrs) -> None:
        """Bump a migration counter AND drop a zero-duration
        ``migration`` marker span — parented under the stream's request
        trace when there is one (same counters==spans discipline as the
        resilience/failover/affinity markers; fault_injection --migrate
        asserts the two agree)."""
        self.migration.bump(decision)
        if record is not None:
            child = record.ctx.child()
            rid, parent = record.request_id, record.ctx.span_id
        else:
            child = TraceContext.root(f"migration:{decision}").child()
            rid, parent = "migration", None
        self.tracer.record(
            rid, "migration", "gateway", 0,
            trace_id=child.trace_id, span_id=child.span_id,
            parent_id=parent, start_ts=time.time(),
            attrs={"decision": decision, **attrs})

    def active_streams(self) -> Dict[str, str]:
        """{request_id: serving lane} for every journaled stream the
        migrate registry currently tracks (tests + diagnostics)."""
        with self._lock:
            return {rid: rec.lane or "?"
                    for rid, rec in self._streams.items()}

    def _migrate_lane_streams(self, name: str, client) -> None:
        """Export every journaled stream the draining lane serves and
        resume each on another lane — concurrently, each under the
        stream's ORIGINAL deadline with a per-transfer timeout. Returns
        once every migration settled (or the overall bound passed);
        per-stream failures have already armed the replay fallback."""
        with self._lock:
            records = [r for r in self._streams.values()
                       if r.lane == name]
        if not records:
            return
        futs = [self._pool().submit(self._migrate_stream, rec, name,
                                    client)
                for rec in records]
        concurrent.futures.wait(
            futs, timeout=self.config.migrate_timeout_s * 2.0 + 10.0)
        # Don't return while a relay has not yet TAKEN its offered
        # continuation: the caller's next step is typically killing the
        # source process (rolling restart), and an unconsumed handoff
        # would lose that race — the relay would hit the dead socket
        # before the migrated terminal and replay instead of splicing.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._lock:
                live = [r for r in records
                        if self._streams.get(r.request_id) is r]
            if not any(r.pending_offer() for r in live):
                break
            time.sleep(0.05)

    def _migrate_stream(self, record: _StreamRecord, source: str,
                        client) -> None:
        """One stream's migration: export off the source (ends the
        source's stream with a ``migrated`` terminal), pick the
        destination by the affinity fingerprint, dispatch the
        continuation, and offer it to the relay thread. EVERY failure
        resolves the handoff as failed — the relay's replay resume
        completes the stream from the journal, which needs nothing from
        either side (both sides' partial state is self-cleaning: export
        releases the source row; a refused import releases its pins and
        fresh blocks before raising)."""
        rid = record.request_id
        self._migration_count(record, "migrations_attempted", lane=source)
        deadline = record.deadline
        budget = self.config.migrate_timeout_s
        if deadline is not None:
            budget = min(budget, max(0.1, deadline.remaining_s()))
        export = None
        refused_cleanly = True
        try:
            reason = "source lane has no migrate surface"
            if client is not None and hasattr(client, "migrate"):
                fut = self._pool().submit(
                    client.migrate, {"request_id": rid}, budget)
                resp = fut.result(timeout=budget + 1.0)
                if resp.get("ok"):
                    export = {k: v for k, v in resp.items()
                              if k not in ("ok", "node_id")}
                else:
                    reason = str(resp.get("reason", "export refused"))
        except Exception as exc:
            refused_cleanly = False  # a late export may still land
            reason = f"export failed: {exc}"
        if export is None:
            # Includes the benign cases (stream just finished, row still
            # prefilling): the relay either never sees a migrated
            # terminal, or replays — both complete the stream. The
            # fallback is armed only when a terminal might still arrive
            # (timeout/transport): a clean worker refusal produces none,
            # and latching a stale failure would poison a later
            # migration window of the still-running stream.
            self._migration_count(record, "export_refusals", lane=source,
                                  reason=reason[:120])
            if not refused_cleanly:
                record.fail(reason)
            return
        try:
            dest = self._pick_migration_dest(record, source)
            if dest is None:
                self._migration_count(record, "destination_unavailable",
                                      lane=source)
                record.fail("no destination lane available")
                return
            cont = {**record.payload, "request_id": rid,
                    "migrate_import": export}
            if deadline is not None:
                cont["deadline_ms"] = max(0.0, deadline.remaining_ms())
            result = self._try_node(dest, cont, op="generate_stream")
            if not _ok(result):
                self._migration_count(record, "import_dispatch_failed",
                                      lane=dest)
                record.fail(f"destination {dest} refused the "
                            f"continuation")
                return
            if not record.offer(result, dest):
                # The relay timed out and owns the replay fallback now:
                # dispose of the orphan continuation so the
                # destination's admission depth and connection release.
                self._dispose_iter(result)
        except Exception as exc:
            self._migration_count(record, "import_dispatch_failed",
                                  lane=source, error=str(exc)[:120])
            record.fail(f"migration failed: {exc}")

    def _pick_migration_dest(self, record: _StreamRecord,
                             source: str) -> Optional[str]:
        """Destination preference: the lane owning the prompt-prefix
        AFFINITY fingerprint (its radix tree most likely already holds
        the prompt's blocks — the import re-adopts them and ships
        less), then the request_id's ring lane, then ring order — the
        first candidate that is present, un-ejected, and
        breaker-admitted; never the source."""
        payload = record.payload
        mdl = payload.get("model")
        with self._lock:
            if mdl is None and len(self._model_rings) > 1:
                mdl = self.default_model
            ring = (self._model_rings.get(str(mdl))
                    if mdl is not None else self._ring)
        if ring is None:
            ring = self._ring
        candidates: List[str] = []
        fp = self._affinity_fingerprint(payload)
        if fp is not None:
            try:
                candidates.append(ring.get_node(fp))
            except RuntimeError:
                pass
        try:
            candidates.append(ring.get_node(record.request_id))
        except RuntimeError:
            pass
        candidates += ring.get_all_nodes()
        seen = set()
        for lane in candidates:
            if lane == source or lane in seen:
                continue
            seen.add(lane)
            if self._lane_admits(lane):
                return lane
        return None

    def _dispose_iter(self, it) -> None:
        """Drain an orphaned stream iterator in the background: running
        it to exhaustion is the one path that releases the serving
        side's admission depth and pooled connection whether or not the
        generator ever started (close() on an unstarted generator skips
        its finally)."""
        def drain():
            try:
                for _ in it:
                    pass
            except Exception:
                pass
            finally:
                try:
                    it.close()
                except Exception:
                    pass
        threading.Thread(target=drain, name="gw-migrate-dispose",
                         daemon=True).start()

    # -- disaggregated prefill/decode serving (DESIGN.md) ----------------------

    def worker_roles(self) -> Dict[str, str]:
        """{lane: role} for every member lane (absent map entry =
        "both") — tests, diagnostics, and the /stats handoff block."""
        with self._lock:
            return {name: self._roles.get(name, "both")
                    for name in self._clients}

    def _disagg_split(self, ring=None):
        """(prefill_capable, decode_capable) lane lists over ``ring``
        (default: the whole fleet), or None unless disagg routing
        should engage: the flag on, at least one DEDICATED prefill
        lane, and at least one decode-capable lane beside it. An
        all-"both" fleet — or disagg off — returns None and routes
        byte-identically to today."""
        if not self.config.disagg:
            return None
        nodes = ring.get_all_nodes() if ring is not None else None
        with self._lock:
            if nodes is None:
                nodes = list(self._clients)
            roles = {n: self._roles.get(n, "both") for n in nodes}
        if not any(r == "prefill" for r in roles.values()):
            return None
        prefill = [n for n in nodes if roles[n] != "decode"]
        decode = [n for n in nodes if roles[n] != "prefill"]
        if not prefill or not decode:
            return None
        return prefill, decode

    def _lane_admits(self, lane: str) -> bool:
        """Present, un-ejected, breaker-admitted — the dispatchability
        gate every handoff candidate walk applies."""
        with self._lock:
            present = lane in self._clients
            ejected = lane in self._ejected
            breaker = self._breakers.get(lane)
        return (present and not ejected and breaker is not None
                and breaker.allow_request())

    def _handoff_count(self, decision: str,
                       record: Optional[_StreamRecord] = None,
                       trace: Optional[_RouteTrace] = None,
                       **attrs) -> None:
        """Bump a handoff counter AND drop a zero-duration
        ``kv_handoff`` marker span — parented under the stream's
        request trace (record) or the route span (trace) when either
        exists. Same counters==spans discipline as the migration
        markers; fault_injection --disagg asserts the two agree."""
        self.handoff.bump(decision)
        if decision not in HandoffCounters.SPAN_FIELDS:
            return
        if record is not None:
            child = record.ctx.child()
            rid, parent = record.request_id, record.ctx.span_id
        elif trace is not None:
            child = trace.ctx.child()
            rid, parent = trace.request_id, trace.ctx.span_id
        else:
            child = TraceContext.root(f"handoff:{decision}").child()
            rid, parent = "handoff", None
        self.tracer.record(
            rid, "kv_handoff", "gateway", 0,
            trace_id=child.trace_id, span_id=child.span_id,
            parent_id=parent, start_ts=time.time(),
            attrs={"decision": decision, **attrs})

    def _handoff_primary(self, ring, ring_primary: str, payload: dict,
                         skip: tuple,
                         trace: Optional[_RouteTrace]) -> str:
        """Disagg primary selection: hash the prompt's affinity
        fingerprint (radix sharing keeps paying fleet-wide) — or the
        request_id when affinity is off / nothing to fingerprint — on
        the PREFILL ring, walking its ring order for the first
        admittable prefill-capable lane. No admittable prefill lane →
        ring order over everyone (``prefill_unavailable``): the request
        serves colocated on whatever lane, today's behavior."""
        split = self._disagg_split(ring)
        if split is None:
            return ring_primary
        prefill_set = set(split[0])
        fp = (self._affinity_fingerprint(payload)
              if self.config.prefix_affinity else None)
        key = fp if fp is not None else str(
            payload.get("request_id") or "")
        candidates: List[str] = []
        try:
            candidates.append(self._prefill_ring.get_node(key))
        except RuntimeError:
            pass
        candidates += self._prefill_ring.get_all_nodes()
        seen = set()
        for lane in candidates:
            if lane in seen or lane in skip or lane not in prefill_set:
                continue
            seen.add(lane)
            if self._lane_admits(lane):
                self._handoff_count("prefill_routed", trace=trace,
                                    lane=lane)
                return lane
        self._handoff_count("prefill_unavailable", trace=trace)
        return ring_primary

    def set_worker_role(self, name: str, role: str) -> dict:
        """/admin/role: flip one lane's serving role at runtime — fleet
        rebalancing under diurnal load. Rides the existing graceful
        machinery: bounded drain first (new admissions shed while the
        flip lands), live streams migrated off when --migrate-streams
        is on, then the worker-side flip, undrain, and the role maps /
        prefill ring update. A failed worker flip restores admissions
        and reports — the lane keeps its old role everywhere."""
        role = str(role)
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be prefill|decode|both, got {role!r}")
        with self._lock:
            client = self._clients.get(name)
        if client is None:
            raise ValueError(f"unknown worker '{name}'")
        drained = False
        if hasattr(client, "drain"):
            fut = self._pool().submit(client.drain)
            try:
                fut.result(timeout=self.config.drain_timeout_s)
                drained = True
            except Exception as exc:
                # Same bounded-drain contract as remove_worker: count
                # it and carry on — the flip itself is still safe.
                self._migration_count(None, "drain_failures", lane=name,
                                      error=str(exc)[:120])
        def _undrain():
            # UNCONDITIONAL (idempotent): a drain call that timed out
            # here may still have landed worker-side moments later —
            # unlike remove_worker, this lane STAYS in the fleet, and
            # a silently-draining member would shed every admission
            # until an operator noticed.
            if hasattr(client, "undrain"):
                try:
                    client.undrain()
                except Exception:
                    pass

        if self.config.migrate_streams:
            try:
                self._migrate_lane_streams(name, client)
            except Exception as exc:
                # A failed migration leg must RESTORE the lane, not
                # strand it draining with its old role half-applied:
                # admissions reopen and both the worker and the gateway
                # role map keep the pre-flip role (per-stream failures
                # inside the leg already armed their replay fallbacks;
                # this catches the leg itself dying).
                _undrain()
                return {"ok": False, "node_id": name,
                        "error": f"migration leg failed: {exc}"[:300]}

        try:
            if hasattr(client, "set_role"):
                client.set_role(role)
            else:
                raise WorkerError("lane has no role surface")
        except Exception as exc:
            _undrain()
            return {"ok": False, "node_id": name,
                    "error": str(exc)[:300]}
        _undrain()
        with self._lock:
            if role == "both":
                self._roles.pop(name, None)
            else:
                self._roles[name] = role
        # Prefill-ring membership follows the role (idempotent ops);
        # re-entry keeps the lane's topology vnode weight.
        if role == "decode":
            self._prefill_ring.remove_node(name)
        elif name not in self._prefill_ring.get_all_nodes():
            self._prefill_ring.add_node(name, self._lane_weight(name))
        self._handoff_count("role_flips", lane=name, role=role)
        return {"ok": True, "node_id": name, "role": role,
                "drained": drained}

    def _handoff_stream(self, record: _StreamRecord,
                        source: Optional[str]) -> None:
        """Steady-state prefill→decode handoff orchestrator (one per
        disagg stream, off the gateway pool): ask the source for an
        export-AFTER-PREFILL (the command parks on its decode loop and
        snapshots the row — first token, sampling state, KV chain — the
        moment prefill completes), pick a decode lane by load, dispatch
        the ``migrate_import`` continuation, and offer it to the relay.
        EVERY failure leaves the stream completable without us: an
        unexported row unparks and decodes locally (colocated
        fallback); an exported-but-unspliced stream lands on the PR 6
        replay resume. Both are byte-identical."""
        rid = record.request_id
        record.handoff = True
        self._handoff_count("handoffs_attempted", record=record,
                            lane=source or "?")
        deadline = record.deadline
        budget = self.config.handoff_timeout_s
        if deadline is not None:
            budget = min(budget, max(0.1, deadline.remaining_s()))
        with self._lock:
            client = self._clients.get(source) if source else None
        export = None
        refused_cleanly = True  # a missing surface produces no terminal
        reason = "source lane has no migrate surface"
        if client is not None and hasattr(client, "migrate"):
            try:
                # Direct call on THIS pool thread: client.migrate is
                # already bounded by its own payload/socket timeouts —
                # a nested pool submit would hold two of the shared
                # 256 workers per in-flight handoff for the whole
                # prefill duration.
                resp = client.migrate(
                    {"request_id": rid, "wait_prefill": True}, budget)
                if resp.get("ok"):
                    export = {k: v for k, v in resp.items()
                              if k not in ("ok", "node_id")}
                else:
                    reason = str(resp.get("reason", "export refused"))
            except Exception as exc:
                # Ambiguous: a timed-out export may still have landed
                # worker-side, so a `migrated` terminal MAY arrive.
                refused_cleanly = False
                reason = f"export failed: {exc}"
        if export is None:
            # Nothing left this lane: cancel any lingering hold so the
            # row resumes local decoding NOW instead of at the park
            # bound, and let the relay keep relaying the source stream.
            self._handoff_count("export_refusals", record=record,
                                lane=source or "?", reason=reason[:120])
            record.handoff = False
            if not refused_cleanly:
                # Arm the relay's fallback ONLY when a migrated
                # terminal might still arrive; a clean refusal produces
                # none, and a latched stale failure would poison a
                # LATER drain migration's handoff window (instant
                # replay instead of awaiting the offer).
                record.fail(reason)
            self._cancel_source_hold(record, client, rid)
            return
        try:
            dests = self._handoff_candidates(record, source)
            if not dests:
                # The row is GONE from the source (exported): the
                # relay's replay resume finishes the stream.
                self._handoff_count("destination_unavailable",
                                    record=record, lane=source or "?")
                record.fail("no decode-capable destination lane")
                return
            cont = {**record.payload, "request_id": rid,
                    "migrate_import": export}
            cont.pop("handoff", None)
            cont.pop("handoff_park_ms", None)
            if deadline is not None:
                cont["deadline_ms"] = max(0.0, deadline.remaining_ms())
            result, dest = None, None
            for cand in dests:
                # A draining/overloaded candidate sheds (_SHED): try
                # the next decode lane instead of abandoning the hop.
                result = self._try_node(cand, cont, op="generate_stream")
                if _ok(result):
                    dest = cand
                    break
            if dest is None:
                self._handoff_count("dispatch_failed", record=record,
                                    lane=dests[0])
                record.fail("every decode lane refused the continuation")
                return
            if not record.offer(result, dest):
                # The relay moved on (timeout → replay fallback owns
                # the stream): dispose of the orphan continuation.
                self._dispose_iter(result)
        except Exception as exc:
            self._handoff_count("dispatch_failed", record=record,
                                lane=source or "?", error=str(exc)[:120])
            record.fail(f"handoff failed: {exc}")

    def _handoff_pool(self) -> concurrent.futures.ThreadPoolExecutor:
        """Dedicated bounded executor for handoff orchestration: each
        orchestrator blocks up to handoff_timeout_s on the export-
        after-prefill call, and riding the shared hedge pool would let
        a disagg burst starve hedged dispatches and drain calls."""
        with self._lock:
            if self._handoff_exec is None:
                self._handoff_exec = \
                    concurrent.futures.ThreadPoolExecutor(
                        max_workers=64, thread_name_prefix="gw-handoff")
            return self._handoff_exec

    def _cancel_colocated_hold(self, record: _StreamRecord,
                               lane: Optional[str]) -> None:
        """A handoff-stamped stream that landed on a non-prefill lane:
        no hop is coming — release the park (or pre-empt it) so local
        decode starts immediately."""
        with self._lock:
            client = self._clients.get(lane) if lane else None
        self._cancel_source_hold(record, client, record.request_id)

    def _cancel_source_hold(self, record: _StreamRecord, client,
                            rid: str) -> None:
        """Best-effort release of a parked source row after a failed
        export (the row would otherwise wait out its park bound before
        resuming local decode)."""
        if client is None or not hasattr(client, "migrate"):
            return
        try:
            resp = client.migrate({"request_id": rid, "cancel": True},
                                  5.0)
            if resp.get("cancelled"):
                self._handoff_count("holds_cancelled", record=record)
        except Exception:
            pass

    def _handoff_candidates(self, record: _StreamRecord,
                            source: Optional[str]) -> List[str]:
        """Decode-capable destination lanes for one handoff, best
        first: fewest journaled active streams (the load signal the
        stream registry already tracks), ring order as the tiebreak;
        never the source, the prober-ejected, or a breaker-open lane
        (draining lanes shed at dispatch — the caller walks to the
        next candidate)."""
        payload = record.payload
        mdl = payload.get("model")
        with self._lock:
            if mdl is None and len(self._model_rings) > 1:
                mdl = self.default_model
            ring = (self._model_rings.get(str(mdl))
                    if mdl is not None else self._ring)
        if ring is None:
            ring = self._ring
        split = self._disagg_split(ring)
        decode = split[1] if split else ring.get_all_nodes()
        with self._lock:
            load: Dict[str, int] = {}
            for rec in self._streams.values():
                if rec.lane:
                    load[rec.lane] = load.get(rec.lane, 0) + 1
        order = {n: i for i, n in enumerate(ring.get_all_nodes())}
        cands = [n for n in decode
                 if n != source and self._lane_admits(n)]
        cands.sort(key=lambda n: (load.get(n, 0),
                                  order.get(n, len(order))))
        return cands

    def _generate_via_handoff(self, payload: dict) -> dict:
        """Blocking /generate under active disaggregation: the prefill
        lane → KV handoff → decode lane path runs as the internal
        stream and collapses into the blocking response shape.
        Admission refusals raise before any consumption (same wire
        classes as the direct dispatch); a terminal error event
        surfaces as the gateway-level failure it is."""
        it = self._stream_with_failover(payload)
        final = None
        try:
            for frame in it:
                evt = _parse_sse(frame)
                if evt is not None and evt.get("done"):
                    final = evt
        finally:
            try:
                it.close()
            except Exception:
                pass
        if final is None:
            raise GatewayError("stream ended without a terminal event")
        if "error" in final:
            raise GatewayError(str(final["error"]))
        return {k: v for k, v in final.items() if k != "done"}

    # -- prefix-affinity routing ----------------------------------------------

    def _affinity_fingerprint(self, payload: dict) -> Optional[str]:
        """Block-aligned fingerprint of the prompt's leading tokens:
        floor(len/affinity_block_size) full blocks, capped at
        affinity_prefix_blocks — the exact granularity the workers'
        radix trees share at, so two requests with equal fingerprints
        have reusable KV blocks in common. None when the prompt has no
        full block (or is malformed — the normal path will 400 it).

        Unified stateless serving rides the same rings: stateless
        payloads (/infer's "input_data", score/embed bodies without
        prompt_tokens) have no token prefix to fingerprint, so this
        returns None and the router degrades gracefully to its
        content-hash / round-robin tiers — no special-case lane class,
        one routing policy for every request family."""
        toks = payload.get("prompt_tokens")
        if not isinstance(toks, (list, tuple)):
            return None
        cfg = self.config
        bs = max(1, int(cfg.affinity_block_size))
        n = min((len(toks) // bs) * bs,
                bs * max(1, int(cfg.affinity_prefix_blocks)))
        if n <= 0:
            return None
        try:
            return "prefix:" + ",".join(str(int(t)) for t in toks[:n])
        except (TypeError, ValueError):
            return None

    def _count_lane_dispatch(self, lane: str) -> None:
        """Stamp one generate-class dispatch on the lane's recent-window
        deque — the load signal the imbalance fallback compares. Only
        kept while that fallback is configured (the sole reader), and
        trimmed on write so a long-lived gateway never accumulates
        beyond one window of timestamps per lane."""
        if int(self.config.affinity_max_imbalance) <= 0:
            return
        now = time.monotonic()
        horizon = now - self.config.affinity_window_s
        with self._lock:
            dq = self._lane_recent.get(lane)
            if dq is None:
                dq = self._lane_recent[lane] = collections.deque()
            while dq and dq[0] < horizon:
                dq.popleft()
            dq.append(now)

    def _recent_dispatches(self, lanes) -> Dict[str, int]:
        horizon = time.monotonic() - self.config.affinity_window_s
        out = {}
        with self._lock:
            for lane in lanes:
                dq = self._lane_recent.get(lane)
                while dq and dq[0] < horizon:
                    dq.popleft()
                out[lane] = len(dq) if dq else 0
        return out

    def _affinity_count(self, trace: Optional[_RouteTrace], decision: str,
                        lane: Optional[str] = None) -> None:
        """Bump an affinity counter AND drop a zero-duration ``affinity``
        marker span under the request's route span (same counters==spans
        discipline as the resilience markers)."""
        self.affinity.bump(decision)
        if trace is not None:
            child = trace.ctx.child()
            attrs = {"decision": decision}
            if lane is not None:
                attrs["lane"] = lane
            self.tracer.record(
                trace.request_id, "affinity", "gateway", 0,
                trace_id=child.trace_id, span_id=child.span_id,
                parent_id=trace.ctx.span_id, start_ts=time.time(),
                attrs=attrs)

    def _affinity_primary(self, ring, ring_primary: str, payload: dict,
                          skip: tuple,
                          trace: Optional[_RouteTrace]) -> str:
        """The affinity half of primary selection: route generate-class
        requests to the lane owning the prompt-prefix fingerprint so
        shared prefixes converge where the KV blocks already live.
        Falls back to ``ring_primary`` (the request_id ring — the exact
        pre-affinity behavior, failover machinery unchanged) when there
        is nothing to fingerprint, the affinity lane is skipped (a
        resume off a dead lane), ejected by the prober, refused by its
        breaker, or running hotter than its least-loaded ring peer by
        more than affinity_max_imbalance recent dispatches."""
        fp = self._affinity_fingerprint(payload)
        if fp is None:
            self._affinity_count(trace, "no_fingerprint")
            return ring_primary
        try:
            lane = ring.get_node(fp)
        except RuntimeError:
            return ring_primary
        if skip and lane in skip:
            # Stream resume: the affinity lane just died mid-stream —
            # ring order takes over (the skip branch of _route_inner).
            self._affinity_count(trace, "resume_skips", lane=lane)
            return ring_primary
        with self._lock:
            ejected = lane in self._ejected
            breaker = self._breakers.get(lane)
        if ejected or breaker is None or not breaker.allow_request():
            self._affinity_count(trace, "ejected_fallbacks", lane=lane)
            return ring_primary
        imb = int(self.config.affinity_max_imbalance)
        if imb > 0 and lane != ring_primary:
            recent = self._recent_dispatches(ring.get_all_nodes())
            if recent.get(lane, 0) - min(recent.values()) >= imb:
                self._affinity_count(trace, "imbalance_fallbacks",
                                     lane=lane)
                return ring_primary
        self._affinity_count(trace, "affinity_routed", lane=lane)
        with self._lock:
            self._affinity_assigned[lane] = (
                self._affinity_assigned.get(lane, 0) + 1)
        return lane

    # -- fleet prefix directory (DESIGN.md "Fleet-wide prefix tier") ----------

    def _prefix_dir_count(self, decision: str,
                          trace: Optional[_RouteTrace] = None,
                          **attrs) -> None:
        """Bump a prefix-directory counter AND drop a zero-duration
        ``prefix_dir`` marker span — under the request's route span when
        one exists (hint attachment, lookup misses), else root-context
        (prober seeds, membership invalidations). Same counters==spans
        discipline as the affinity/fleet markers; fault_injection
        --fleet-prefix asserts the two agree."""
        self.prefix_dir.bump(decision)
        span_attrs = {"decision": decision,
                      **{k: v for k, v in attrs.items() if v is not None}}
        if trace is not None:
            child = trace.ctx.child()
            self.tracer.record(
                trace.request_id, "prefix_dir", "gateway", 0,
                trace_id=child.trace_id, span_id=child.span_id,
                parent_id=trace.ctx.span_id, start_ts=time.time(),
                attrs=span_attrs)
        else:
            ctx = TraceContext.root(f"prefix_dir:{decision}").child()
            self.tracer.record(
                "prefix_dir", "prefix_dir", "gateway", 0,
                trace_id=ctx.trace_id, span_id=ctx.span_id,
                start_ts=time.time(), attrs=span_attrs)

    def _seed_prefix_dir(self, lane: str, summaries) -> None:
        """Turn one lane's bounded /health radix summaries into
        directory entries (prober sweep seeding). One ``seeded``
        bump+span per sweep that changed anything — per-entry spans
        would drown the recorder at probe cadence; ``evictions`` is a
        span-free value counter for the same reason."""
        if not isinstance(summaries, list) or not summaries:
            return
        recorded = evicted = deepest = 0
        for entry in summaries[:32]:
            if not isinstance(entry, dict):
                continue
            fp = self._affinity_fingerprint(
                {"prompt_tokens": entry.get("tokens")})
            try:
                blocks = int(entry.get("blocks", 0))
            except (TypeError, ValueError):
                continue
            if fp is None or blocks <= 0:
                continue
            with self._lock:
                if lane not in self._clients:
                    return  # removed mid-sweep: nothing to advertise
                cur = self._prefix_dir.lookup(fp)
                if (cur is not None and cur["lane"] == lane
                        and cur["blocks"] >= blocks):
                    continue  # already known this deep; LRU-touched
                evicted += self._prefix_dir.record(fp, lane, blocks)
            recorded += 1
            deepest = max(deepest, blocks)
        if evicted:
            self.prefix_dir.bump("evictions", evicted)
        if recorded:
            self._prefix_dir_count("seeded", lane=lane,
                                   entries=recorded, deepest=deepest)

    def _attach_prefix_hint(self, payload: dict, primary: str,
                            trace: Optional[_RouteTrace]) -> None:
        """Stamp the directory's owner lane onto a generate-class
        payload as ``prefix_hint`` so the SERVING lane — wherever ring
        order, affinity, or failover actually lands the request — can
        pull the owner's KV chain peer-to-peer instead of re-prefilling
        it. No hint when the prompt has no full block, the directory
        names nobody (or the entry went stale), or the owner IS the
        chosen primary (the request lands on the blocks already). The
        hint rides the payload through failover: a retry lane benefits
        exactly like the primary."""
        fp = self._affinity_fingerprint(payload)
        if fp is None:
            return  # nothing a radix tree could share at block grain
        with self._lock:
            entry = self._prefix_dir.lookup(fp)
            client = (self._clients.get(entry["lane"])
                      if entry is not None else None)
        if entry is None or client is None:
            self._prefix_dir_count("lookup_misses", trace=trace)
            return
        if entry["lane"] == primary:
            return  # affinity already converged us onto the owner
        hint = {"lane": entry["lane"], "fingerprint": fp,
                "blocks": int(entry["blocks"])}
        addr = getattr(client, "url", None)
        if addr:
            hint["addr"] = addr
        payload["prefix_hint"] = hint
        self._prefix_dir_count("hints_attached", trace=trace,
                               lane=entry["lane"],
                               blocks=int(entry["blocks"]))

    def _record_prefix_owner(self, payload: dict, lane: str) -> None:
        """Post-completion directory update: the lane that just served a
        generate-class dispatch indexed this prompt in its radix tree at
        admission, so it now owns the fingerprint's chain. The record
        keeps a live DEEPER entry on another lane (a prober-seeded deep
        chain must not be demoted by a shallow completion); an unchanged
        entry is LRU-touched without a bump (bounded span volume)."""
        fp = self._affinity_fingerprint(payload)
        if fp is None:
            return
        toks = payload.get("prompt_tokens") or ()
        bs = max(1, int(self.config.affinity_block_size))
        blocks = len(toks) // bs
        if blocks <= 0:
            return
        with self._lock:
            if lane not in self._clients:
                return
            cur = self._prefix_dir.lookup(fp)
            if (cur is not None and cur["lane"] == lane
                    and cur["blocks"] >= blocks):
                return
            evicted = self._prefix_dir.record(fp, lane, blocks)
        if evicted:
            self.prefix_dir.bump("evictions", evicted)
        self._prefix_dir_count("recorded", lane=lane, blocks=blocks)

    def _route(self, payload: dict, op: str, skip: tuple = (),
               out_info: Optional[dict] = None) -> dict:
        """``skip``: lanes excluded from dispatch for this route (the
        stream-resume path skips the lane that just died mid-stream).
        ``out_info``: optional dict the dispatch layer fills with
        ``{"lane": name}`` on success — the resume journal needs to know
        which lane served a stream to skip it on the next attempt."""
        # In-flight gauge + shed-rate window (overload control only — a
        # defaults-only gateway pays nothing): the gauge covers the
        # request's whole residency — blocking ops until their response,
        # streams until their event iterator finishes (the decrement is
        # handed to a wrapper below), so a stream-heavy fleet's gauge
        # actually fills and tier admission/pressure stay live.
        overload_on = (self.config.overload_control
                       or self._tenant_bucket is not None)
        with self._lock:
            self._total_requests += 1
            if overload_on:
                self._inflight += 1
        self._retry_budget.record_request()
        # Anonymous requests get a stable server-side request_id (minted
        # once, forwarded to the lane, echoed in the response) instead of
        # the old route-on-a-random-key: the id doubles as the trace root,
        # so even an id-less request is correlatable end to end.
        rid = payload.get("request_id")
        if rid is None:
            rid = uuid.uuid4().hex
            payload = {**payload, "request_id": rid}
        request_id = str(rid)
        trace = _RouteTrace(request_id, TraceContext.from_request(payload))
        t0 = time.perf_counter()
        start = time.time()
        handed_off = False
        try:
            result = self._route_inner(payload, op, request_id, trace,
                                       skip=skip, out_info=out_info)
            trace.outcome = "ok"
            if (overload_on and op == "generate_stream"
                    and hasattr(result, "__iter__")):
                # The stream occupies the gauge until its iterator
                # settles; the wrapper owns the decrement from here.
                result = self._inflight_watched(result)
                handed_off = True
            return result
        except ShedError as exc:
            trace.outcome = exc.kind
            raise
        except Exception:
            trace.outcome = "error"
            raise
        finally:
            if overload_on:
                if not handed_off:
                    with self._lock:
                        self._inflight -= 1
                # Congestion refusals (not deadline expiries, not faults)
                # feed the shed-rate pressure window.
                self._shed_stats.record(trace.outcome == "overloaded")
            self.tracer.record(
                request_id, "route", "gateway",
                (time.perf_counter() - t0) * 1e6,
                trace_id=trace.ctx.trace_id, span_id=trace.ctx.span_id,
                parent_id=(trace.parent.span_id if trace.parent is not None
                           else None),
                start_ts=start, attrs={"op": op, "outcome": trace.outcome})

    def _count(self, trace: Optional[_RouteTrace], decision: str) -> None:
        """Bump a resilience counter AND drop a zero-duration marker span
        under the request's route span — the counters say how often, the
        markers say for WHICH requests (tools/fault_injection.py asserts
        the two agree)."""
        self.resilience.bump(decision)
        if trace is not None:
            child = trace.ctx.child()
            self.tracer.record(
                trace.request_id, "resilience", "gateway", 0,
                trace_id=child.trace_id, span_id=child.span_id,
                parent_id=trace.ctx.span_id, start_ts=time.time(),
                attrs={"decision": decision})

    def _route_inner(self, payload: dict, op: str, request_id: str,
                     trace: _RouteTrace, skip: tuple = (),
                     out_info: Optional[dict] = None) -> dict:
        # Deadline admission: an already-expired request sheds HERE — one
        # cheap 503 + Retry-After instead of a doomed dispatch chain (and,
        # downstream, a burned batch row).
        deadline = Deadline.from_request(
            payload, default_ms=self.config.default_deadline_ms)
        if deadline is not None and deadline.expired():
            self._count(trace, "deadline_rejected")
            exc = self._shed(DeadlineExceeded(
                "deadline exceeded at gateway admission"))
            exc.stage = "gateway_admission"
            raise exc
        # Overload control (default off): per-tenant rate limiting and
        # priority-tiered admission against the in-flight gauge — the
        # lowest tier sheds first, and every refusal carries a
        # load-derived Retry-After.
        if self._tenant_bucket is not None or self.config.overload_control:
            self._overload_admit(payload, trace)
        # "model" restricts routing AND failover to that model's sub-ring;
        # without the field, multi-model gateways use the deterministic
        # default model, single-model gateways the global ring.
        mdl = payload.get("model")
        probing = False  # model unknown to the gateway; workers validate
        with self._lock:
            multi = len(self._model_rings) > 1
            untyped = bool(self._untyped)
            if mdl is None and multi:
                mdl = self.default_model
            if mdl is not None:
                ring = self._model_rings.get(str(mdl))
                if ring is None and untyped:
                    # Workers with unknown models (HTTP URLs carry no
                    # metadata) might serve it: probe the global ring and
                    # let each worker's _check_model decide — a mismatch
                    # fails over instead of 400ing a servable request.
                    ring, probing = self._ring, True
            else:
                ring = self._ring
            # Snapshot the served-model list for the error below while
            # the lock is still held — iterating the live dict after
            # release races add_worker/remove_worker.
            known = sorted(self._model_rings) if ring is None else ()
        if ring is None:
            raise ValueError(            # wire 400, not a lane failure
                f"unknown model '{mdl}'; serving {known}")
        try:
            primary = ring.get_node(request_id)
        except RuntimeError:  # every lane of this model was removed
            raise GatewayError(f"no workers available for model '{mdl}'")
        if payload.get("handoff") and op == "generate_stream":
            # Disaggregated first segment: the prefill ring owns
            # primary selection (affinity fingerprint folded in), with
            # ring order over everyone as the colocated fallback.
            primary = self._handoff_primary(ring, primary, payload,
                                            skip, trace)
        elif (self.config.prefix_affinity
                and op in ("generate", "generate_stream")):
            primary = self._affinity_primary(ring, primary, payload,
                                             skip, trace)
        # Fleet prefix tier: AFTER primary selection (any flavor) the
        # directory gets one shot at stamping a peer-fetch hint — the
        # tier is routing-neutral (never changes which lane serves, only
        # what the serving lane can skip re-prefilling).
        if (self._prefix_dir_on
                and op in ("generate", "generate_stream")
                and "prefix_hint" not in payload):
            self._attach_prefix_hint(payload, primary, trace)

        if skip and primary in skip:
            # The resume path excludes the lane that just failed its
            # stream: go straight to ring-order failover (budgeted and
            # deadline-bounded like any other failover march).
            with self._lock:
                self._failovers += 1
            return self._failover(ring, primary, payload, op, probing,
                                  deadline, skip=skip, trace=trace,
                                  out_info=out_info)
        if self.config.hedge_enabled and op in _HEDGEABLE_OPS:
            return self._route_hedged(ring, primary, payload, op,
                                      probing, deadline, trace)
        result = self._try_node(primary,
                                self._with_deadline(payload, deadline),
                                op=op, probing=probing, trace=trace,
                                out_info=out_info, ring=ring)
        if not _ok(result):
            with self._lock:
                self._failovers += 1
            result = self._failover(ring, primary, payload, op,
                                    probing, deadline, skip=skip,
                                    shed_seen=result is _SHED, trace=trace,
                                    out_info=out_info)
        return result

    def _shed(self, exc):
        """Stamp a shed-class exception with the Retry-After hint: the
        configured constant, or — with overload control on — that base
        scaled by measured pressure (monotone: the more saturated the
        fleet, the longer clients are told to stay away)."""
        if self.config.overload_control:
            exc.retry_after_s = load_retry_after(
                self.config.shed_retry_after_s, self._overload_pressure())
        else:
            exc.retry_after_s = self.config.shed_retry_after_s
        return exc

    # -- overload control ------------------------------------------------------

    def _inflight_watched(self, it):
        """Relay a stream iterator unchanged, decrementing the in-flight
        gauge exactly once when it finishes (exhaustion, error, or the
        client closing early)."""
        def watched():
            try:
                yield from it
            finally:
                with self._lock:
                    self._inflight -= 1
        return watched()

    def _overload_pressure(self) -> float:
        """Measured congestion in [0, inf): the in-flight gauge's fill
        fraction when one is configured, else the recent shed rate —
        either way 0 when idle and growing with actual refusal risk."""
        if self.config.overload_max_inflight > 0:
            with self._lock:
                inflight = self._inflight
            return inflight / self.config.overload_max_inflight
        return self._shed_stats.pressure()

    def _overload_count(self, trace: Optional[_RouteTrace], decision: str,
                        **attrs) -> None:
        """Bump an overload counter AND drop a zero-duration ``overload``
        marker span under the request's route span (same counters==spans
        discipline as the resilience/affinity markers; fault_injection
        --overload asserts the two agree)."""
        self.overload.bump(decision)
        if trace is not None:
            child = trace.ctx.child()
            self.tracer.record(
                trace.request_id, "overload", "gateway", 0,
                trace_id=child.trace_id, span_id=child.span_id,
                parent_id=trace.ctx.span_id, start_ts=time.time(),
                attrs={"decision": decision, **attrs})

    def _overload_admit(self, payload: dict,
                        trace: Optional[_RouteTrace]) -> None:
        """Gateway overload admission, cheapest check first. Order
        matters: the tenant bucket refuses a flooding tenant even while
        the fleet has headroom (fairness is not a congestion question);
        tier admission then sheds lowest-tier-first as the in-flight
        gauge fills, and only a gauge at its full limit refuses
        top-tier work."""
        cfg = self.config
        if self._tenant_bucket is not None:
            tenant = str(payload.get("tenant", "default"))
            ok, wait = self._tenant_bucket.allow(tenant)
            if not ok:
                self._overload_count(trace, "rate_limited", tenant=tenant)
                exc = self._shed(Overloaded(
                    f"tenant '{tenant}' over its rate limit "
                    f"({cfg.tenant_rate:g} req/s)"))
                # The bucket knows its actual refill time; never suggest
                # retrying sooner than a token can exist.
                exc.retry_after_s = max(exc.retry_after_s, wait)
                exc.cause = "rate_limit"
                exc.stage = "gateway_admission"
                raise exc
        if not cfg.overload_control:
            return
        # Unknown value -> wire 400 whenever the master switch is on,
        # gauge or no gauge — a typo'd priority must never silently ride
        # as routable traffic (MIGRATION.md documents the contract).
        tier = parse_priority(payload)
        limit = cfg.overload_max_inflight
        if limit <= 0:
            return  # no gauge: tier admission off, validation only
        with self._lock:
            inflight = self._inflight  # includes this request
        if inflight > limit:
            self._overload_count(trace, "shed_depth",
                                 tier=TIER_NAMES[tier])
            exc = self._shed(Overloaded(
                f"gateway at max in-flight {limit}"))
            exc.cause = "depth"
            exc.stage = "gateway_admission"
            raise exc
        if (tier < len(TIER_ADMIT_FRAC) - 1
                and inflight > tier_limit(limit, tier)):
            self._overload_count(trace, "shed_tier",
                                 tier=TIER_NAMES[tier])
            exc = self._shed(Overloaded(
                f"gateway shedding priority tier '{TIER_NAMES[tier]}' "
                f"at {inflight}/{limit} in flight"))
            exc.cause = "tier"
            exc.stage = "gateway_admission"
            raise exc

    @staticmethod
    def _with_deadline(payload: dict, deadline: Optional[Deadline]) -> dict:
        """Deadline propagation: each dispatch carries the budget REMAINING
        at dispatch time (recomputed per attempt, so retries after backoff
        forward a smaller number). No deadline → payload untouched, wire
        bytes identical to the pre-resilience gateway."""
        if deadline is None:
            return payload
        return {**payload, "deadline_ms": max(0.0, deadline.remaining_ms())}

    def _failover(self, ring, primary: str, payload: dict, op: str,
                  probing: bool, deadline: Optional[Deadline],
                  skip: tuple = (), shed_seen: bool = False,
                  trace: Optional[_RouteTrace] = None,
                  out_info: Optional[dict] = None) -> dict:
        """Ring-order failover across every other lane (gateway.cpp:51-59)
        — now deadline-bounded, budgeted, and backed off: each attempt
        consumes the global retry budget (failover storms cannot amplify
        an outage past `1 + ratio`), sleeps an exponential+jittered delay
        (base 0 = reference's immediate march), and stops the moment the
        client's budget is gone. A march where at least one lane SHED
        (rather than failed) terminates as Overloaded (wire 503 +
        Retry-After): fleet congestion must read as back-off-and-retry,
        never as an outage."""
        cfg = self.config
        attempt = 0
        for node in ring.get_all_nodes():
            if node == primary or node in skip:
                continue
            if deadline is not None and deadline.expired():
                self._count(trace, "deadline_expired")
                exc = self._shed(DeadlineExceeded(
                    "deadline exceeded during failover"))
                exc.stage = "failover"
                raise exc
            if not self._retry_budget.try_acquire():
                self._count(trace, "retry_budget_exhausted")
                if shed_seen:
                    # A lane SHED this request before the budget ran out:
                    # the march is ending under congestion, and congestion
                    # must surface as 503 + Retry-After (back off and
                    # retry), never the 500-class outage below.
                    exc = self._shed(Overloaded(
                        "retry budget exhausted after a lane shed the "
                        "request (overloaded, not failed)"))
                    exc.stage = "failover"
                    raise exc
                raise GatewayError(
                    "retry budget exhausted (retries capped at "
                    f"{cfg.retry_budget_ratio:.0%} of recent requests)")
            delay = backoff_delay(attempt, cfg.retry_backoff_base_ms,
                                  cfg.retry_backoff_max_ms,
                                  cfg.retry_jitter)
            if delay > 0:
                if deadline is not None:
                    delay = min(delay, max(0.0, deadline.remaining_s()))
                self._count(trace, "backoff_waits")
                time.sleep(delay)
            self._count(trace, "retries")
            result = self._try_node(node,
                                    self._with_deadline(payload, deadline),
                                    op=op, probing=probing, trace=trace,
                                    kind="retry", out_info=out_info,
                                    ring=ring)
            if _ok(result):
                return result
            shed_seen = shed_seen or result is _SHED
            attempt += 1
        if shed_seen:
            exc = self._shed(Overloaded(
                "all lanes shed the request (overloaded or draining)"))
            exc.stage = "failover"
            raise exc
        raise GatewayError("All workers failed or unavailable")

    def _pool(self) -> concurrent.futures.ThreadPoolExecutor:
        # Generous cap: with hedging on, EVERY hedgeable dispatch rides
        # this pool (1-2 threads per in-flight request), and the serving
        # front is thread-per-request with no cap of its own — an
        # undersized pool would throttle overall concurrency, not just
        # hedges. 256 sits far above the stdlib front's practical
        # concurrency; threads spawn on demand, so idle cost is zero.
        with self._lock:
            if self._hedge_pool is None:
                self._hedge_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=256, thread_name_prefix="gw-hedge")
            return self._hedge_pool

    def _lane_tracker(self, node: str) -> LatencyTracker:
        with self._lock:
            tracker = self._latency.get(node)
            if tracker is None:
                tracker = self._latency[node] = LatencyTracker()
            return tracker

    def _hedge_threshold_s(self, primary: Optional[str] = None) -> float:
        """When to give up waiting on `primary`: the best OTHER lane's
        latency quantile — "hedge once the primary exceeds what a healthy
        alternative would take at p-q" — floored at hedge_min_ms (and
        pure hedge_min_ms until some other lane has enough samples).
        Excluding the primary's own window keeps a degraded lane from
        raising its own threshold. primary=None (stats) uses all lanes."""
        cfg = self.config
        thr = cfg.hedge_min_ms / 1000.0
        with self._lock:
            trackers = [t for n, t in self._latency.items() if n != primary]
        quantiles = [t.quantile(cfg.hedge_quantile) for t in trackers
                     if len(t) >= cfg.hedge_min_samples]
        quantiles = [q for q in quantiles if q is not None]
        if quantiles:
            thr = max(thr, min(quantiles))
        return thr

    def _route_hedged(self, ring, primary: str, payload: dict, op: str,
                      probing: bool, deadline: Optional[Deadline],
                      trace: Optional[_RouteTrace] = None) -> dict:
        """Hedged dispatch (idempotent ops only): wait `threshold` on the
        primary; if it is merely SLOW — the failure mode breakers cannot
        see — fire the next ring lane and take whichever answers first.
        The loser's result is discarded ("cancelled" at the routing layer;
        its lane simply finishes and the breaker records its outcome).
        Hedges consume the retry budget, so a quantile collapse cannot
        double fleet load. Tracing: primary and hedge dispatches record
        sibling ``attempt`` spans (same trace_id, distinct span_ids) under
        the route span."""
        pool = self._pool()
        p_started = threading.Event()
        t_start: list = [None]

        def _primary_task():
            t_start[0] = time.perf_counter()
            p_started.set()
            return self._try_node(primary,
                                  self._with_deadline(payload, deadline),
                                  op, probing, trace=trace, kind="primary",
                                  ring=ring)

        p_fut = pool.submit(_primary_task)

        def _record_primary(fut):
            # Feed the quantile EVERY primary completion (measured from its
            # dispatch start), wherever the route ended up: recording only
            # within-threshold successes would censor the sample at the
            # threshold and pin it at hedge_min_ms forever; recording
            # whole-route time would inflate it with backoff/failover
            # exactly when lanes degrade.
            try:
                r = fut.result()
            except BaseException:
                return
            if _ok(r) and t_start[0] is not None:
                self._lane_tracker(primary).record(
                    time.perf_counter() - t_start[0])

        p_fut.add_done_callback(_record_primary)
        # Arm the hedge timer only once the dispatch actually STARTED: a
        # saturated pool queues tasks, and hedging a primary that never
        # ran would amplify load against perfectly healthy lanes — the
        # exact spiral hedging must not feed.
        if not p_started.wait(timeout=None if deadline is None
                              else max(0.0, deadline.remaining_s())):
            # The task never started (saturated pool): cancel it so the
            # queued thunk doesn't later dispatch a request nobody will
            # read — abandoned dispatches against an already-saturated
            # fleet are the amplification spiral this wait guards.
            p_fut.cancel()
            self._count(trace, "deadline_expired")
            raise self._shed(DeadlineExceeded(
                "deadline exceeded before primary dispatch started"))
        thr = self._hedge_threshold_s(primary)
        deadline_clamped = (deadline is not None
                            and deadline.remaining_s() < thr)
        if deadline_clamped:
            thr = max(0.0, deadline.remaining_s())
        try:
            result = p_fut.result(timeout=thr)
        except concurrent.futures.TimeoutError:
            if deadline_clamped:
                # The wait ended because the CLIENT's budget ran out, not
                # because the lane exceeded the latency threshold: a hedge
                # here would burn a shared retry-budget token dispatching
                # a request the hedge lane must immediately shed. Ride out
                # the remaining budget on the primary instead.
                return self._await_primary(p_fut, ring, primary, payload,
                                           op, probing, deadline, trace)
            result = None
        else:
            if _ok(result):
                return result  # latency recorded by the done-callback
            # Primary failed FAST (dead or shedding lane): plain budgeted
            # failover.
            with self._lock:
                self._failovers += 1
            return self._failover(ring, primary, payload, op, probing,
                                  deadline, shed_seen=result is _SHED,
                                  trace=trace)

        # Primary exceeded the hedge threshold. Pick the next lane whose
        # breaker admits traffic; no budget, no lane → ride out the primary.
        hedge_node = next(
            (n for n in ring.get_all_nodes()
             if n != primary and self._breaker_allows(n)), None)
        if hedge_node is None or not self._retry_budget.try_acquire():
            if hedge_node is not None:
                self._count(trace, "retry_budget_exhausted")
            return self._await_primary(p_fut, ring, primary, payload, op,
                                       probing, deadline, trace)
        self._count(trace, "hedges")
        h_fut = pool.submit(self._try_node, hedge_node,
                            self._with_deadline(payload, deadline),
                            op, probing, trace=trace, kind="hedge",
                            ring=ring)
        pending = {p_fut: primary, h_fut: hedge_node}
        first_error: Optional[BaseException] = None
        shed_seen = False
        while pending:
            timeout = (None if deadline is None
                       else max(0.0, deadline.remaining_s()))
            done, _ = concurrent.futures.wait(
                list(pending), timeout=timeout,
                return_when=concurrent.futures.FIRST_COMPLETED)
            if not done:  # deadline ran out waiting on both lanes
                self._count(trace, "deadline_expired")
                raise self._shed(DeadlineExceeded(
                    "deadline exceeded awaiting hedged dispatch"))
            for fut in done:
                pending.pop(fut)
                try:
                    result = fut.result()
                except BaseException as exc:
                    first_error = first_error or exc
                    continue
                if _ok(result):
                    self._count(trace, "hedge_wins" if fut is h_fut
                                else "hedge_losses")
                    return result
                shed_seen = shed_seen or result is _SHED
        # Both lanes failed/shed: budgeted failover over the remainder.
        with self._lock:
            self._failovers += 1
        try:
            return self._failover(ring, primary, payload, op, probing,
                                  deadline, skip=(hedge_node,),
                                  shed_seen=shed_seen, trace=trace)
        except GatewayError:
            if first_error is not None:
                raise first_error
            raise

    def _await_primary(self, p_fut, ring, primary, payload, op, probing,
                       deadline: Optional[Deadline],
                       trace: Optional[_RouteTrace] = None) -> dict:
        """Hedge unavailable: block on the primary alone (deadline-bounded),
        then fall back to plain failover if it ultimately failed."""
        timeout = (None if deadline is None
                   else max(0.0, deadline.remaining_s()))
        try:
            result = p_fut.result(timeout=timeout)
        except concurrent.futures.TimeoutError:
            self._count(trace, "deadline_expired")
            raise self._shed(DeadlineExceeded(
                "deadline exceeded awaiting primary lane"))
        if _ok(result):
            return result
        with self._lock:
            self._failovers += 1
        return self._failover(ring, primary, payload, op, probing, deadline,
                              shed_seen=result is _SHED, trace=trace)

    def _breaker_allows(self, node: str) -> bool:
        with self._lock:
            breaker = self._breakers.get(node)
        return breaker is not None and breaker.allow_request()

    def _try_node(self, node: str, payload: dict, op: str = "infer",
                  probing: bool = False,
                  trace: Optional[_RouteTrace] = None,
                  kind: str = "primary",
                  out_info: Optional[dict] = None,
                  ring=None) -> Optional[dict]:
        """Breaker-gated dispatch (reference tryNode, gateway.cpp:80-128).
        Returns None on failure so the caller can fail over. `probing`:
        the gateway couldn't resolve the request's model itself, so a
        worker's model-mismatch rejection (a client-class 4xx/ValueError)
        means "try the next lane" — no breaker penalty, no terminal 400.

        Tracing: each dispatch records an ``attempt`` span (child of the
        route span; ``kind`` = primary | retry | hedge, sibling attempts
        share the trace_id with distinct span_ids). When the CLIENT
        supplied a traceparent, the attempt's own context is re-forwarded
        in the payload — worker-side spans then parent under this exact
        attempt; traceless payloads are forwarded untouched."""
        with self._lock:
            client = self._clients.get(node)
            breaker = self._breakers.get(node)
            ejected = node in self._ejected
        if client is None or breaker is None:
            return None
        if ejected:
            # The health prober took this lane out of rotation: skip it
            # like a failed dispatch (the caller fails over) with no
            # breaker penalty — ejection is the prober's reversible call.
            # Fail OPEN when probe evidence alone has ejected every lane
            # of THIS request's ring (e.g. a fleet-wide compile stall
            # tripping a tight scheduler_stall_s): ejection is only
            # honored while at least one peer remains in rotation, so
            # request evidence — the breakers — stays the last word on a
            # total outage. Per-ring, not fleet-wide: one model's lanes
            # all dying must fail open for THAT model even while other
            # models' lanes are healthy.
            peers = ring.get_all_nodes() if ring is not None else None
            with self._lock:
                if peers is None:
                    peers = list(self._clients)
                all_ejected = all(p in self._ejected for p in peers)
            if not all_ejected:
                return None
        if not breaker.allow_request():
            return None
        ctx = None
        if trace is not None:
            ctx = trace.ctx.child()
            if trace.traced:
                payload = {**payload, "traceparent": ctx.to_traceparent()}
        t0 = time.perf_counter()
        start = time.time()
        outcome = "error"

        def _span():
            if trace is not None:
                self.tracer.record(
                    trace.request_id, "attempt", "gateway",
                    (time.perf_counter() - t0) * 1e6,
                    trace_id=ctx.trace_id, span_id=ctx.span_id,
                    parent_id=trace.ctx.span_id, start_ts=start,
                    attrs={"lane": node, "kind": kind, "outcome": outcome})

        try:
            response = getattr(client, op)(payload)
            breaker.record_success()
            outcome = "ok"
            if (self.config.prefix_affinity
                    and op in ("generate", "generate_stream")):
                self._count_lane_dispatch(node)
            if (self._prefix_dir_on
                    and op in ("generate", "generate_stream")):
                # Post-completion update: this lane's radix tree indexed
                # the prompt at admission — record it as the owner so
                # the NEXT shared-prefix request can fetch from here.
                self._record_prefix_owner(payload, node)
            if out_info is not None:
                out_info["lane"] = node
            return response
        except WorkerError:
            breaker.record_failure()
            outcome = "failed"
            return None
        except Overloaded:
            # The lane SHED the request (queue full / draining): healthy
            # but busy — fail over without a breaker penalty (a breaker
            # trip would amplify the overload into an outage).
            self._count(trace, "shed_overloaded")
            outcome = "shed"
            return _SHED
        except DeadlineExceeded as exc:
            # The client's budget is gone; no other lane can help. A
            # lane_suspect expiry (the lane HELD the request past its
            # budget without answering — hang signature) still feeds the
            # breaker so a dead lane loses its hash share; a clean worker
            # 503 does not.
            if getattr(exc, "lane_suspect", False):
                breaker.record_failure()
            self._count(trace, "deadline_expired")
            outcome = "deadline"
            shed = self._shed(DeadlineExceeded(
                f"deadline exceeded at lane {node}"))
            shed.stage = "lane"
            raise shed
        except ValueError:
            if probing:
                outcome = "wrong_model"
                return None  # wrong-model lane; healthy — no penalty
            raise
        finally:
            _span()

    # -- observability --------------------------------------------------------

    def _resilience_configured(self) -> bool:
        cfg = self.config
        return (cfg.default_deadline_ms is not None or cfg.hedge_enabled
                or cfg.retry_budget_ratio is not None
                or cfg.retry_backoff_base_ms > 0)

    def get_stats(self) -> dict:
        """Exact /stats schema (``gateway.cpp:63-77``).

        Every membership-adjacent snapshot (breakers, role map,
        topology block, lane list, affinity totals, in-flight gauge,
        fleet degraded map) is taken under ONE ``_lock`` acquisition —
        the same idiom as the PR 8 ``_route_inner`` fix. Snapshotting
        them piecemeal let a concurrent add/remove land between the
        acquisitions, publishing a torn read: a lane present in the
        ``handoff.roles`` map but missing from ``topology.ring_weights``
        (or vice versa) within one response body."""
        with self._lock:
            items = list(self._breakers.items())
            total, failovers = self._total_requests, self._failovers
            active_streams = len(self._streams)
            lanes = sorted(self._clients)
            roles = {n: self._roles.get(n, "both") for n in lanes}
            topo = dict(self._topology)
            topo_updates = self._topology_updates
            aff_assigned = dict(self._affinity_assigned)
            inflight = self._inflight
            fleet_degraded = dict(self._fleet_degraded)
            fleet_pressure = self._fleet_pressure
            prefix_dir_state = (self._prefix_dir.stats()
                                if self._prefix_dir is not None else None)
        out = {
            "total_workers": len(items),
            # Additive fields (reference /stats has only total_workers +
            # circuit_breakers; extra keys don't break its parsers).
            "total_requests": total,
            "failovers": failovers,
            "circuit_breakers": [
                {
                    "node": node,
                    "state": br.state_name(),
                    "failures": br.failure_count,
                    "successes": br.success_count,
                }
                for node, br in items
            ],
        }
        # Additive, and only once the resilience layer is configured or
        # has made a decision (deadline-carrying request, shed, retry,
        # hedge): a defaults-only deployment's /stats stays byte-identical
        # to the breaker-only schema above.
        if self._resilience_configured() or self.resilience.any_nonzero():
            res = self.resilience.as_dict()
            if self._retry_budget.enabled:
                res["retry_budget"] = self._retry_budget.stats()
            if self.config.hedge_enabled:
                res["hedge_threshold_ms"] = round(
                    self._hedge_threshold_s() * 1000.0, 3)
            out["resilience"] = res
        # Additive "failover" block (crash-tolerant streaming + prober),
        # present only once the feature is configured or has decided
        # something — defaults-only /stats stays byte-identical.
        if (self.config.failover_streams
                or self.config.health_probe_interval_s > 0
                or self.failover.any_nonzero()):
            fo = self.failover.as_dict()
            fo["ejected_lanes"] = self.ejected_lanes()
            out["failover"] = fo
        # Additive "migration" block (live stream migration + the
        # bounded-drain counter), same gating discipline.
        if self.config.migrate_streams or self.migration.any_nonzero():
            mig = self.migration.as_dict()
            mig["active_streams"] = active_streams
            out["migration"] = mig
        # Additive "handoff" block (disaggregated prefill/decode
        # serving), same gating discipline: present only once
        # configured or exercised.
        if self.config.disagg or self.handoff.any_nonzero():
            ho = self.handoff.as_dict()
            ho["roles"] = roles
            out["handoff"] = ho
        # Additive "topology" block (topology-aware ring), present only
        # once any lane carries a mesh-shape label — an all-single-chip
        # fleet's /stats stays byte-identical. Reports each labelled
        # lane's mesh shape plus every lane's vnode weight, so an
        # operator can see exactly how the ring maps chips.
        if topo:
            out["topology"] = {
                "lanes": topo,
                "ring_weights": {n: max(1, self._ring.node_weight(n))
                                 for n in lanes},
                "updates": topo_updates,
            }
        # Additive "affinity" block (prefix-affinity routing), same
        # gating discipline: a defaults-only /stats stays byte-identical.
        if self.config.prefix_affinity or self.affinity.any_nonzero():
            aff = self.affinity.as_dict()
            aff["assigned"] = aff_assigned
            out["affinity"] = aff
        # Additive "prefix_directory" block (fleet prefix tier), same
        # gating discipline: present only with --prefix-fetch (the
        # counters can't move while the directory is None), so a
        # defaults-off /stats stays byte-identical.
        if prefix_dir_state is not None or self.prefix_dir.any_nonzero():
            pd = self.prefix_dir.as_dict()
            if prefix_dir_state is not None:
                pd.update(prefix_dir_state)
            out["prefix_directory"] = pd
        # Additive "overload" block (adaptive overload control), same
        # gating discipline: present only once configured or exercised.
        if (self.config.overload_control or self._tenant_bucket is not None
                or self.overload.any_nonzero()):
            ov = self.overload.as_dict()
            ov["pressure"] = round(self._overload_pressure(), 4)
            ov["inflight"] = inflight
            if self.config.overload_max_inflight > 0:
                ov["max_inflight"] = self.config.overload_max_inflight
            if self._tenant_bucket is not None:
                ov["tenants"] = self._tenant_bucket.tenants()
            out["overload"] = ov
        # Additive "fleet" block (elastic fleet: autoscaler +
        # /admin/fleet), same gating discipline: present only once the
        # controller is configured or a fleet decision was made.
        if self.config.autoscale or self.fleet.any_nonzero():
            fl = self.fleet.as_dict()
            fl["lanes"] = len(lanes)
            fl["degraded"] = fleet_degraded
            if fleet_pressure is not None:
                fl["pressure"] = fleet_pressure
            out["fleet"] = fl
        # Additive "slo" block (observability plane): present only once
        # latency objectives are configured — windowed error-budget burn
        # over the histograms the fleet already keeps, zero new
        # measurement paths. Defaults-off /stats stays byte-identical.
        if self._slo is not None:
            slo = self.slo_status()
            if slo is not None:
                out["slo"] = slo
        # Additive "trace_ledger" block: which streams the stitcher can
        # currently reassemble (present only with --trace-stitch).
        if self._ledger is not None:
            out["trace_ledger"] = self._ledger.summary()
        return out
