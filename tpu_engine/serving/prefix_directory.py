"""Fleet-wide KV prefix directory (DESIGN.md "Fleet-wide prefix tier").

A bounded fingerprint -> {lane, blocks, generation} map the gateway keeps
beside its rings: which lane's radix tree holds the deepest known KV
chain for each block-aligned prompt fingerprint. The directory is a
HINT CACHE, not a source of truth — every consumer (the peer-fetch path
in the scheduler) verifies checksum + geometry before trusting a byte,
and every miss/stale/refused outcome falls back to local prefill. That
is why entries are invalidated by cheap per-lane GENERATION stamps
instead of eagerly tracked: bumping a lane's generation (removal,
drain, eject, recovery) voids all of its entries at once, and a voided
entry found later simply drops out of the map.

All methods assume the caller holds ``Gateway._lock`` — the directory
is one more piece of routing state under the gateway's single snapshot
lock (tools/analyze/registry.py pins this).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional


class PrefixDirectory:
    """LRU-bounded fingerprint -> owner map with per-lane generation
    invalidation. Pure state, no threads, no locks of its own."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(1, int(capacity))
        # fp -> {"lane", "blocks", "generation"}; insertion order is the
        # LRU order (lookups/records move touched entries to the end).
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._lane_gen: dict = {}  # lane -> current generation stamp

    def lane_generation(self, lane: str) -> int:
        return self._lane_gen.get(lane, 0)

    def record(self, fp: str, lane: str, blocks: int) -> int:
        """Record (or refresh) the owner for ``fp``. A live existing
        entry naming a DEEPER chain on another lane is kept — the
        directory tracks the best-known owner, and post-completion
        updates must not demote a prober-seeded deep chain to a
        shallower one. Returns entries evicted by the LRU bound."""
        blocks = max(0, int(blocks))
        gen = self._lane_gen.setdefault(lane, 0)
        cur = self._entries.get(fp)
        if cur is not None:
            stale = self._lane_gen.get(cur["lane"], -1) != cur["generation"]
            if not stale and cur["lane"] != lane \
                    and cur["blocks"] > blocks:
                self._entries.move_to_end(fp)
                return 0
        self._entries[fp] = {"lane": lane, "blocks": blocks,
                             "generation": gen}
        self._entries.move_to_end(fp)
        evicted = 0
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
            evicted += 1
        return evicted

    def lookup(self, fp: str) -> Optional[dict]:
        """The live entry for ``fp`` (LRU-touched), or None. A stale
        entry — its lane's generation moved since it was recorded — is
        dropped on the way out (lazy invalidation backstop; eager
        sweeps in ``invalidate_lane`` keep counts honest)."""
        e = self._entries.get(fp)
        if e is None:
            return None
        if self._lane_gen.get(e["lane"], -1) != e["generation"]:
            del self._entries[fp]
            return None
        self._entries.move_to_end(fp)
        return dict(e)

    def invalidate_lane(self, lane: str) -> int:
        """Void every entry naming ``lane`` (removal / drain / eject /
        recovery — its radix tree can no longer be trusted to hold what
        the directory promised). Bumps the lane's generation so any
        entry that escapes the eager sweep dies lazily in ``lookup``.
        Returns entries dropped."""
        self._lane_gen[lane] = self._lane_gen.get(lane, 0) + 1
        dead = [fp for fp, e in self._entries.items() if e["lane"] == lane]
        for fp in dead:
            del self._entries[fp]
        return len(dead)

    def stats(self) -> dict:
        per_lane: dict = {}
        for e in self._entries.values():
            per_lane[e["lane"]] = per_lane.get(e["lane"], 0) + 1
        return {"entries": len(self._entries), "capacity": self.capacity,
                "lanes": per_lane}
