"""Process composition: wire workers/gateway to HTTP servers.

Three launchable shapes:

- ``serve_worker`` — one worker lane behind HTTP (reference
  ``worker_node <port> <node_id> [model]``, ``worker_node.cpp:145-204``);
- ``serve_gateway`` — routing gateway over remote HTTP workers (reference
  ``gateway <worker:port> ...``, ``gateway.cpp:161-200``);
- ``serve_combined`` — the TPU-native shape: one process, one HTTP front
  door, N in-process lanes pinned round-robin onto the local chips
  (SURVEY.md §7 design stance). No per-request HTTP between gateway and
  lanes; the hash ring selects a lane directly.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from tpu_engine.serving.autoscaler import (InProcessLaneProvider,
                                           StandbyLaneProvider)
from tpu_engine.serving.gateway import Gateway
from tpu_engine.serving.http import JsonHttpServer
from tpu_engine.serving.worker import WorkerNode
from tpu_engine.utils.config import GatewayConfig, WorkerConfig
from tpu_engine.utils.deadline import ShedError
from tpu_engine.utils.metrics import render_prometheus
from tpu_engine.utils.tracing import export_chrome, stitch_trace


def model_from_path(path_or_name: str) -> str:
    """Map a reference-style model path (e.g. models/resnet50-v2-7.onnx) to a
    registry name so reference launch lines work unchanged."""
    from tpu_engine.models.registry import available_models, _ensure_builtin_models_imported

    _ensure_builtin_models_imported()
    names = available_models()
    if path_or_name in names:
        return path_or_name
    base = path_or_name.rsplit("/", 1)[-1].lower()
    for name in names:
        if name in base.replace("-", "").replace("_", ""):
            return name
    for name in names:  # resnet50-v2-7.onnx → resnet50
        if base.startswith(name[: max(4, len(name) - 2)]):
            return name
    raise ValueError(f"cannot map '{path_or_name}' to a registered model {names}")


def serve_worker(config: WorkerConfig, background: bool = True) -> Tuple[WorkerNode, JsonHttpServer]:
    worker = WorkerNode(config)
    server = JsonHttpServer(config.port)
    server.route("POST", "/infer", lambda body: (200, worker.handle_infer_raw(body)))
    server.route("POST", "/generate", lambda body: (200, worker.handle_generate(body)))
    server.route("POST", "/generate/stream",
                 lambda body: (200, worker.handle_generate_stream(body)))
    server.route("GET", "/health", lambda _body: (200, worker.get_health()))
    server.route("GET", "/metrics", lambda _body: (
        200, render_prometheus([worker.get_health()],
                               recorders={worker.node_id: worker.tracer},
                               named_hists=worker.latency_histograms()),
        "text/plain; version=0.0.4"))
    server.route("GET", "/trace", lambda _body: (200, {
        "summary": {worker.node_id: worker.tracer.summary()},
        "recent": worker.tracer.recent(20),
        "stages": {worker.node_id: worker.tracer.stage_summary()},
    }))
    server.route("GET", "/trace/export", lambda _body: (
        200, export_chrome({worker.node_id: worker.tracer})))
    server.route("POST", "/admin/reload", lambda body: (
        200, worker.reload_weights(body["model_path"])))
    server.route("POST", "/score", lambda body: (
        200, worker.handle_score(body)))

    # Drain (lame-duck): refuse new admissions with 503 + Retry-After while
    # in-flight work completes — the graceful half of removing a worker
    # from a gateway's ring (the reference's only removal is SIGKILL).
    def _admin_drain(body):
        action = (body or {}).get("action", "drain")
        if action == "drain":
            status = worker.drain()
        elif action == "undrain":
            status = worker.undrain()
        else:
            return 400, {"error": "action must be drain|undrain"}
        # "status" names the idempotent outcome (draining /
        # already-draining / undrained / not-draining) — double-drain
        # and undrain-of-idle answer it instead of re-running effects.
        return 200, {"ok": True, "node_id": worker.node_id,
                     "draining": worker.draining, "status": status}

    server.route("POST", "/admin/drain", _admin_drain)
    # Live stream migration (DESIGN.md): export one live stream's row —
    # the gateway's migrate-mode drain drives this per stream; the
    # continuation rides /generate/stream with a `migrate_import` body.
    server.route("POST", "/admin/migrate",
                 lambda body: (200, worker.handle_migrate_export(body or {})))
    # Fleet prefix tier (DESIGN.md "Fleet-wide prefix tier"): serve this
    # lane's longest radix chain matching a peer's token prefix — the
    # peer verifies checksum + geometry before trusting a byte, so the
    # export itself never refuses on trust grounds (only on drain /
    # non-paged / no-match, as named non-raising statuses).
    server.route("POST", "/admin/export_prefix",
                 lambda body: (200, worker.handle_export_prefix(body or {})))
    # Disaggregated serving: flip the lane's role at runtime (the
    # gateway's set_worker_role rides drain + migrate around this).
    server.route("POST", "/admin/role",
                 lambda body: (200, worker.set_role((body or {}).get(
                     "role", ""))))
    # Observability plane (DESIGN.md): the per-tick flight recorder
    # (GET = ring contents, POST {"dump": reason} = forced postmortem)
    # and the tick-bounded jax.profiler capture (needs --profile-dir;
    # POST {"ticks": N} | {"action": "stop"|"status"}).
    server.route("GET", "/admin/timeline",
                 lambda body: (200, worker.handle_timeline(body)))
    server.route("POST", "/admin/timeline",
                 lambda body: (200, worker.handle_timeline(body or {})))
    server.route("POST", "/admin/profile",
                 lambda body: (200, worker.handle_profile(body or {})))
    server.route("GET", "/admin/profile",
                 lambda body: (200, worker.handle_profile(
                     {"action": "status"})))
    # Cross-lane stitching, single-lane flavor: only this lane's
    # fragments (the gateway's /admin/trace merges the whole fleet).
    server.route_prefix(
        "GET", "/admin/trace/",
        lambda _body, rid: (200, stitch_trace(
            {worker.node_id: worker.tracer.snapshot()}, rid)))
    _print_worker_banner(worker, config)
    server.start(background=background)
    return worker, server


def serve_gateway(worker_urls: List[str], config: Optional[GatewayConfig] = None,
                  background: bool = True,
                  standby_workers: Optional[List[str]] = None,
                  ) -> Tuple[Gateway, JsonHttpServer]:
    """``standby_workers``: pre-launched worker ADDRESSES the elastic
    fleet controller may bring into (and out of) rotation — the warm
    pool behind ``--autoscale`` in gateway mode. They are NOT registered
    at startup; the probe gate admits them on scale-up."""
    config = config or GatewayConfig()
    gateway = Gateway(worker_urls, config)
    server = JsonHttpServer(config.port)
    server.route("POST", "/infer", lambda body: (200, gateway.route_request_raw(body)))
    server.route("POST", "/generate", lambda body: (200, gateway.route_generate(body)))
    server.route("POST", "/generate/stream",
                 lambda body: (200, gateway.route_generate_stream(body)))
    server.route("GET", "/stats", lambda _body: (200, gateway.get_stats()))
    server.route("POST", "/score", lambda body: (200, gateway.route_score(body)))
    server.route("GET", "/metrics", lambda _body: (
        200, render_prometheus([], gateway.get_stats(),
                               recorders={"gateway": gateway.tracer}),
        "text/plain; version=0.0.4"))
    server.route("GET", "/trace", lambda _body: (200, {
        "summary": {"gateway": gateway.tracer.summary()},
        "recent": gateway.tracer.recent(20),
        "stages": {"gateway": gateway.tracer.stage_summary()},
    }))
    server.route("GET", "/trace/export", lambda _body: (
        200, export_chrome({"gateway": gateway.tracer})))
    # Disaggregated serving: flip a lane's role fleet-side — the
    # gateway drains + migrates streams off the lane around the flip.
    server.route("POST", "/admin/role", lambda body: (
        200, gateway.set_worker_role((body or {}).get("node", ""),
                                     (body or {}).get("role", ""))))
    # Elastic fleet (DESIGN.md "Elastic fleet"): the operator surface —
    # status / add (probe-then-register) / remove (drain+migrate
    # retire) / rebalance (role flip) / clear (degraded state). Works
    # with or without --autoscale; every failure is a named,
    # non-raising status.
    server.route("POST", "/admin/fleet", lambda body: (
        200, gateway.fleet_admin(body or {})))
    # Observability plane: the merged cross-lane stitch (fragments
    # pulled from each lane's /trace/export — best-effort on dead
    # lanes) and the SLO burn status. Both answer with their flags
    # off: the stitch falls back to request_id correlation; /admin/slo
    # names the missing objectives instead of 404ing.
    server.route_prefix(
        "GET", "/admin/trace/",
        lambda _body, rid: (200, gateway.stitched_trace(rid)))
    server.route("GET", "/admin/slo", lambda _body: (
        200, gateway.slo_status()
        or {"error": "no objectives configured "
                     "(set --slo-ttft-p99-ms / --slo-itl-p99-ms / "
                     "--slo-completion-p99-ms)"}))
    if config.autoscale or standby_workers:
        gateway.engage_autoscaler(
            provider=StandbyLaneProvider(list(standby_workers or [])))
    print(f"Gateway listening on port {config.port}")
    print(f"Workers: {len(worker_urls)}")
    print("Circuit breakers enabled")
    print("Ready!")
    server.start(background=background)
    return gateway, server


def parse_mesh_spec(spec: str):
    """'data=8' / 'model=2,data=4' → Mesh over the local devices. A missing
    ``data`` axis is added with size 1 so the engine's batch-scatter axis
    always exists."""
    from tpu_engine.parallel.mesh import create_mesh

    axes = []
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes.append((name.strip(), int(size)))
    if "data" not in (n for n, _ in axes):
        axes.append(("data", 1))
    return create_mesh(shape=tuple(s for _, s in axes),
                       axis_names=tuple(n for n, _ in axes))


def _mesh_engine(model: str, lane_cfg: WorkerConfig, mesh, params=None):
    """One engine spanning the whole mesh: batches scatter over ``data``
    (ICI, XLA collectives — the north-star's in-process replacement for the
    reference's HTTP worker fan-out), weights shard over ``model`` when that
    axis is >1 (answering the reference's dead ``shard_id`` stub,
    worker_node.cpp:32)."""
    from tpu_engine.models.registry import create_model, _ensure_builtin_models_imported
    from tpu_engine.runtime.engine import InferenceEngine
    from tpu_engine.training.train import shard_params_tp

    _ensure_builtin_models_imported()
    import jax

    spec = create_model(model)
    if params is None:
        params = spec.init(jax.random.PRNGKey(0))
    shardings = None
    if mesh.shape.get("model", 1) > 1:
        shardings = shard_params_tp(params, mesh, axis="model")
    return InferenceEngine(
        spec,
        params=params,
        dtype=lane_cfg.dtype,
        batch_buckets=lane_cfg.batch_buckets,
        shape_buckets=lane_cfg.shape_buckets,
        mesh=mesh,
        param_shardings=shardings,
    )


def serve_combined(
    model: str = "resnet50",
    lanes: int = 0,
    port: int = 8000,
    worker_config: Optional[WorkerConfig] = None,
    gateway_config: Optional[GatewayConfig] = None,
    background: bool = True,
    warmup: bool = False,
    native_front: Optional[bool] = None,
    mesh=None,
    lane_roles: Optional[List[str]] = None,
):
    """One process: HTTP front door + in-process lanes over local devices.

    ``lanes=0`` means one lane per local device. Lanes share nothing but the
    host process: each has its own cache, batcher and engine pinned to a chip
    (round-robin when lanes > devices).

    ``mesh`` (spec string like 'data=8' / 'model=2,data=4', or a
    jax.sharding.Mesh) switches to mesh-sharded serving: ONE lane whose
    engine spans all mesh devices — the dynamic batcher aggregates requests
    and each batch is scattered over the ``data`` axis / computed against
    ``model``-sharded weights in a single XLA dispatch.

    ``lane_roles`` (disaggregated serving): per-lane serving roles
    assigned round-robin, e.g. ["prefill", "prefill", "decode",
    "decode"] — pair with a ``--disagg`` gateway config so fresh
    generate work lands on prefill lanes and finished KV chains ship to
    decode lanes. None (default) uses ``worker_config.role`` uniformly.
    """
    import jax

    devices = jax.devices()
    gateway_config = gateway_config or GatewayConfig(port=port)
    # Multi-model serving: "a,b" assigns models to lanes round-robin;
    # requests carry {"model": "..."} and the gateway routes on per-model
    # sub-rings (Triton-style — the reference is one model per worker).
    models = [m.strip() for m in str(model).split(",") if m.strip()]
    if len(models) > 1 and worker_config is not None \
            and worker_config.model_path:
        raise ValueError("model_path is ambiguous with multiple models; "
                         "serve them from separate processes or extend "
                         "the config per model")
    # Real weights (HF/torch/orbax) are loaded once and shared by every lane
    # (each engine device_puts its own copy onto its chip).
    params = None
    if worker_config is not None and worker_config.model_path:
        from tpu_engine.serving.worker import _load_model_path

        params = _load_model_path(models[0], worker_config.model_path)
    workers = []
    if mesh is not None:
        if isinstance(mesh, str):
            mesh = parse_mesh_spec(mesh)
        if len(models) > 1:
            raise ValueError("mesh-sharded serving is single-model")
        cfg = worker_config or WorkerConfig()
        lane_cfg = WorkerConfig(**{**cfg.__dict__, "node_id": "worker_1",
                                   "model": models[0]})
        engine = _mesh_engine(models[0], lane_cfg, mesh, params=params)
        workers.append(WorkerNode(lane_cfg, engine=engine))
        n_lanes = 1
    else:
        if lanes and lanes < len(models):
            raise ValueError(
                f"lanes={lanes} cannot serve {len(models)} models — "
                f"later-listed models would silently get no lane")
        tp = int(getattr(worker_config, "tp", 1) or 1) \
            if worker_config is not None else 1
        if tp > 1:
            # Tensor-parallel lanes each span a tp-device mesh slice:
            # the default fleet is devices // tp lanes, not one per
            # chip (the "lanes are chips" rule becomes "virtual nodes
            # are chips" — the gateway ring weights them that way).
            n_lanes = lanes or max(1, len(devices) // tp, len(models))
        else:
            n_lanes = lanes or max(len(devices), len(models))
        if lane_roles and lanes and lanes < len(lane_roles):
            raise ValueError(
                f"lanes={lanes} cannot honor {len(lane_roles)} lane "
                f"roles — later-listed roles would silently get no lane")
        if lane_roles:
            n_lanes = max(n_lanes, len(lane_roles))
        for i in range(n_lanes):
            cfg = worker_config or WorkerConfig()
            over = {"node_id": f"worker_{i+1}",
                    "model": models[i % len(models)]}
            if lane_roles:
                over["role"] = lane_roles[i % len(lane_roles)]
            if tp > 1:
                # Disjoint mesh slices per lane (round-robin when an
                # explicit --lanes oversubscribes): lane i owns devices
                # [i*tp, (i+1)*tp) — without this every lane would
                # stack its mesh on devices [0, tp).
                n_slices = max(1, len(devices) // tp)
                over["tp_device_offset"] = (i % n_slices) * tp
            lane_cfg = WorkerConfig(**{**cfg.__dict__, **over})
            from tpu_engine.runtime.engine import InferenceEngine

            engine = InferenceEngine(
                lane_cfg.model,
                params=params,
                dtype=lane_cfg.dtype,
                batch_buckets=lane_cfg.batch_buckets,
                shape_buckets=lane_cfg.shape_buckets,
                quantize=lane_cfg.quantize,
                device=devices[i % len(devices)],
            )
            workers.append(WorkerNode(lane_cfg, engine=engine))
    if warmup:
        # Pre-compile every batch bucket before accepting traffic — the
        # reference pays its graph compile at session load the same way
        # (inference_engine.cpp:31). Lanes pinned to the same device share
        # XLA's compile cache, so this is ~one compile per bucket.
        for w in workers:
            w.engine.warmup()
            if getattr(w.generator, "_stateless", False):
                # Stateless-family scheduler: no generation lane to
                # warm — engine.warmup() above already compiled every
                # one-shot bucket the single-tick rows dispatch into.
                continue
            if w.generator is not None:
                # Also compile the generation lane (smallest prompt bucket
                # + one decode chunk) — a cold /generate otherwise pays
                # tens of seconds of XLA compiles on its first request.
                # Straight to the generator: the worker's request path would
                # pollute the reference-exact /health counters and the trace
                # with a phantom request.
                try:
                    w.generator.generate([[1, 2, 3]], max_new_tokens=2)
                except Exception as exc:  # warmup is best-effort
                    print(f"generate warmup skipped: {exc}")
    gateway = Gateway(workers, gateway_config)
    # Fleet prefix tier, combined-mode transport: in-process lanes have
    # no URL to dial, so a peer fetch is a direct handle_export_prefix
    # call on the owning lane object. Any lookup/shape surprise raises
    # and the caller classifies it as peer_unreachable (local prefill).
    prefix_fetch_on = bool(worker_config is not None
                           and getattr(worker_config,
                                       "gen_prefix_fetch", False))

    def _peer_export(hint, payload):
        lane = hint.get("lane")
        for w in list(workers):
            if w.node_id == lane:
                return w.handle_export_prefix(payload)
        raise KeyError(f"no in-process lane named {lane!r}")

    if prefix_fetch_on:
        for w in workers:
            w.set_prefix_fetch_transport(_peer_export)
    if gateway_config.autoscale and mesh is None:
        # Elastic fleet in combined mode: the provider mints fresh
        # in-process lanes with the same config/device round-robin the
        # startup loop used (indices continue past the static fleet so
        # names never collide), and retired lanes are stopped and
        # dropped from the per-lane surfaces.
        from tpu_engine.runtime.engine import InferenceEngine

        base_lanes = n_lanes

        def _spawn_lane(idx):
            i = base_lanes + idx
            cfg = worker_config or WorkerConfig()
            over = {"node_id": f"worker_{i+1}",
                    "model": models[i % len(models)]}
            if lane_roles:
                over["role"] = lane_roles[i % len(lane_roles)]
            if tp > 1:
                n_slices = max(1, len(devices) // tp)
                over["tp_device_offset"] = (i % n_slices) * tp
            lane_cfg = WorkerConfig(**{**cfg.__dict__, **over})
            engine = InferenceEngine(
                lane_cfg.model,
                params=params,
                dtype=lane_cfg.dtype,
                batch_buckets=lane_cfg.batch_buckets,
                shape_buckets=lane_cfg.shape_buckets,
                quantize=lane_cfg.quantize,
                device=devices[i % len(devices)],
            )
            w = WorkerNode(lane_cfg, engine=engine)
            if prefix_fetch_on:
                w.set_prefix_fetch_transport(_peer_export)
            workers.append(w)
            return w

        def _drop_lane(w):
            try:
                workers.remove(w)
            except ValueError:
                pass

        gateway.engage_autoscaler(provider=InProcessLaneProvider(
            _spawn_lane,
            max_lanes=gateway_config.autoscale_max_lanes,
            on_retire=_drop_lane))
    routes = {}
    routes[("POST", "/infer")] = lambda body: (200, gateway.route_request_raw(body))
    routes[("POST", "/generate")] = lambda body: (200, gateway.route_generate(body))
    routes[("POST", "/generate/stream")] = (
        lambda body: (200, gateway.route_generate_stream(body)))

    def _stats(_body):
        """Gateway /stats, plus per-lane paged-KV pool, mixed-step, and
        speculative-decoding health when a decode lane runs them
        (additive keys; the reference-exact schema is untouched for
        dense deployments)."""
        out = gateway.get_stats()
        kv, mixed, spec, state, pfetch = {}, {}, {}, {}, {}
        stateless = {}
        for w in workers:
            gen = getattr(w, "generator", None)
            if gen is None or not hasattr(gen, "stats"):
                continue
            try:
                st = gen.stats()
            except Exception:
                continue
            if st.get("stateless", {}).get("dispatches"):
                # Unified stateless serving: one-shot row counters per
                # lane, present only once a lane actually dispatched a
                # single-tick row (defaults-off /stats is untouched).
                stateless[w.node_id] = st["stateless"]
            if st.get("kv_pool"):
                kv[w.node_id] = st["kv_pool"]
            if st.get("prefix_fetch"):
                # Fleet prefix tier, lane half: peer-fetch attempts and
                # fallback rungs per lane (present only once a hint was
                # acted on — defaults-off /stats is untouched).
                pfetch[w.node_id] = st["prefix_fetch"]
            if st.get("state_pool"):
                # state_slab-family lanes (models.ssd): the kv_pool
                # analog — gated the same way, absent on kv_paged
                # fleets.
                state[w.node_id] = st["state_pool"]
            if st.get("mixed"):
                mixed[w.node_id] = dict(st["mixed"],
                                        active=st.get("active"))
            if st.get("spec"):
                spec[w.node_id] = dict(st["spec"],
                                       active=st.get("active"))
        if kv:
            out["kv_pool"] = kv
        if state:
            out["state_pool"] = state
        if mixed:
            out["mixed"] = mixed
        if spec:
            out["spec"] = spec
        if pfetch:
            out["prefix_fetch"] = pfetch
        if stateless:
            out["stateless"] = stateless
        return 200, out

    routes[("GET", "/stats")] = _stats
    # Lane health is addressable through the gateway process in combined mode.
    for w in workers:
        routes[("GET", f"/health/{w.node_id}")] = lambda _b, w=w: (200, w.get_health())

    def _aggregate_health(_b):
        """Whole-process /health: counters summed over lanes (so reference
        tooling scraping one worker URL per process reports truthfully),
        plus a per-lane breakdown. Field names stay reference-exact."""
        lanes_h = [w.get_health() for w in workers]
        total = sum(h["total_requests"] for h in lanes_h)
        hits = sum(h["cache_hits"] for h in lanes_h)
        bp_keys = ("total_batches", "timeout_batches", "full_batches")
        bp = {k: sum(h["batch_processor"][k] for h in lanes_h) for k in bp_keys}
        n_batches = bp["total_batches"]
        bp["avg_batch_size"] = round(
            sum(h["batch_processor"]["avg_batch_size"]
                * h["batch_processor"]["total_batches"]
                for h in lanes_h) / n_batches, 4) if n_batches else 0.0
        agg_hit_rate = (sum(h["cache_hit_rate"] * h["total_requests"]
                            for h in lanes_h) / total) if total else 0.0
        return 200, {
            "healthy": all(h["healthy"] for h in lanes_h),
            "node_id": lanes_h[0]["node_id"] if len(lanes_h) == 1 else "combined",
            "total_requests": total,
            "cache_hits": hits,
            "cache_size": sum(h["cache_size"] for h in lanes_h),
            "cache_hit_rate": round(agg_hit_rate, 6),
            "batch_processor": bp,
            "lanes": {h["node_id"]: h for h in lanes_h},
        }

    routes[("GET", "/health")] = _aggregate_health

    # Fault injection (BASELINE config 5). The reference injects faults by
    # killing worker processes (README.md:322-349); in-process lanes expose
    # an explicit admin hook instead: {"node": "worker_1", "action":
    # "fail"|"heal"|"slow"}. "slow" adds {"latency_s": X} of delay per
    # request WITHOUT failing — the slow-lane fault breakers cannot see,
    # which the resilience layer (deadlines/hedging) exists to answer.
    def _admin_fault(body):
        node = body.get("node")
        action = body.get("action", "fail")
        targets = [w for w in workers if w.node_id == node or node in (None, "*")]
        if not targets:
            return 404, {"error": f"unknown node '{node}'"}
        for w in targets:
            if action == "fail":
                w.inject_fault()
            elif action == "slow":
                w.inject_latency(float(body.get("latency_s", 1.0)))
            else:
                w.heal()
        return 200, {"ok": True, "nodes": [w.node_id for w in targets],
                     "action": action}

    routes[("POST", "/admin/fault")] = _admin_fault

    # Drain (lame-duck) mode: {"node": "worker_1"|"*", "action":
    # "drain"|"undrain", "remove": false}. "remove": true additionally
    # takes the drained lane off the hash ring (graceful removal — the
    # resilience-layer answer to the reference's kill-the-process).
    def _admin_drain(body):
        node = body.get("node")
        action = body.get("action", "drain")
        if action not in ("drain", "undrain"):
            return 400, {"error": "action must be drain|undrain"}
        targets = [w for w in workers
                   if w.node_id == node or node in (None, "*")]
        if not targets:
            # Named, non-raising: draining a lane that is not a member
            # is an idempotent no-op (it may have been retired between
            # the operator's read and this call), not a 404 surprise.
            return 200, {"ok": False, "status": "unknown-lane",
                         "node": node}
        for w in targets:
            if action == "drain":
                if body.get("remove") and gateway.config.migrate_streams:
                    # Migrate-mode graceful removal: remove_worker owns
                    # the whole ladder — bounded drain, per-stream KV
                    # handoff, then ring removal (DESIGN.md "Live
                    # stream migration").
                    gateway.remove_worker(w.node_id, drain=True)
                    continue
                w.drain()
                if body.get("remove"):
                    # Already drained above — plain ring removal (the
                    # drain=True flavor would drain the same lane twice).
                    gateway.remove_worker(w.node_id)
            else:
                w.undrain()
        return 200, {"ok": True, "action": action,
                     "nodes": [w.node_id for w in targets],
                     "removed": bool(body.get("remove"))
                     and action == "drain"}

    routes[("POST", "/admin/drain")] = _admin_drain

    # Role flips (disaggregated serving): {"node": "worker_1", "role":
    # "prefill"|"decode"|"both"} — the gateway rides /admin/drain +
    # stream migration around the flip so live streams move, not break.
    def _admin_role(body):
        node = (body or {}).get("node")
        role = (body or {}).get("role", "")
        if not any(w.node_id == node for w in workers):
            return 404, {"error": f"unknown node '{node}'"}
        return 200, gateway.set_worker_role(node, role)

    routes[("POST", "/admin/role")] = _admin_role

    # Elastic fleet operator surface (DESIGN.md "Elastic fleet") —
    # status / add / remove / rebalance / clear; named, non-raising
    # statuses. Active with or without --autoscale.
    routes[("POST", "/admin/fleet")] = lambda body: (
        200, gateway.fleet_admin(body or {}))

    # Tracing (SURVEY.md §5: the reference has only per-request wall
    # clocks). "summary"/"recent" keep the original schema; "gateway" and
    # "stages" (per-stage queue_wait / batch_form / device_compute
    # breakdown, scraped by bench.py) are additive.
    def _trace(_body):
        return 200, {
            "summary": {w.node_id: w.tracer.summary() for w in workers},
            "recent": [s for w in workers for s in w.tracer.recent(20)],
            "gateway": gateway.tracer.summary(),
            "stages": {w.node_id: w.tracer.stage_summary()
                       for w in workers},
        }

    def _trace_export(_body):
        recs = {w.node_id: w.tracer for w in workers}
        recs["gateway"] = gateway.tracer
        return 200, export_chrome(recs)

    def _admin_profile(body):
        from tpu_engine.utils import tracing

        body = body or {}
        if body.get("action") == "start":
            return 200, tracing.profiler_start(body.get("log_dir", "/tmp/tpu_engine_profile"))
        if body.get("action") == "stop":
            return 200, tracing.profiler_stop()
        # Tick-bounded capture (observability plane): {"ticks": N
        # [, "node": id]} arms ONE lane's scheduler to stop the trace
        # after exactly N ticks — the bounded stages onchip_campaign.py
        # drives (needs the lane's --profile-dir). {"action": "status"}
        # reports ticks left + the last capture.
        node = body.get("node")
        targets = [w for w in workers
                   if node in (None, "*") or w.node_id == node]
        if not targets:
            return 404, {"error": f"unknown node '{node}'"}
        if body.get("action") == "status" or body.get("ticks"):
            return 200, targets[0].handle_profile(body)
        return 400, {"error": "action must be start|stop|status, "
                              "or pass ticks"}

    # Flight recorder (observability plane): GET = every lane's tick
    # ring; POST {"dump": reason[, "node": id]} = forced postmortem.
    def _admin_timeline(body):
        body = body or {}
        node = body.get("node")
        targets = [w for w in workers
                   if node in (None, "*") or w.node_id == node]
        if not targets:
            return 404, {"error": f"unknown node '{node}'"}
        return 200, {"lanes": {w.node_id: w.handle_timeline(body)
                               for w in targets}}

    routes[("GET", "/trace")] = _trace
    routes[("GET", "/trace/export")] = _trace_export
    routes[("POST", "/admin/profile")] = _admin_profile
    routes[("GET", "/admin/timeline")] = _admin_timeline
    routes[("POST", "/admin/timeline")] = _admin_timeline
    def _named_hists():
        named = {}
        for w in workers:
            for name, by_node in w.latency_histograms().items():
                named.setdefault(name, {}).update(by_node)
        return named

    routes[("GET", "/metrics")] = lambda _b: (
        200, render_prometheus([w.get_health() for w in workers],
                               gateway.get_stats(),
                               recorders={**{w.node_id: w.tracer
                                             for w in workers},
                                          "gateway": gateway.tracer},
                               named_hists=_named_hists()),
        "text/plain; version=0.0.4")

    # Hot weight reload (no serving pause; the reference restarts worker
    # processes to change weights). {"model_path": ..., "node": optional,
    # "model": optional} — all lanes by default. The checkpoint loads from
    # disk ONCE; each lane then swaps independently, and per-node outcomes
    # are reported even on partial failure (an error mid-fleet must not
    # hide which lanes already serve the new weights). In a multi-model
    # deployment a bare reload is ambiguous — the checkpoint is loaded
    # against ONE architecture, and two models that happen to share tree
    # structure/shapes would silently accept each other's weights (swap
    # validates only treedef/shape/dtype) — so the caller must name the
    # target with "model" or "node" when more than one model is served.
    def _admin_reload(body):
        from tpu_engine.serving.worker import _load_model_path

        node = body.get("node")
        targets = [w for w in workers
                   if node in (None, "*") or w.node_id == node]
        if not targets:
            return 404, {"error": f"unknown node '{node}'"}
        model = body.get("model")
        if model is not None:
            targets = [w for w in targets
                       if getattr(w.engine.spec, "name", None) == model]
            if not targets:
                return 404, {"error": f"no lane serves model '{model}'"}
        else:
            served = {getattr(w.engine.spec, "name", None) for w in targets}
            if len(served) > 1:
                return 400, {"error":
                             "multiple models served "
                             f"({sorted(str(s) for s in served)}): "
                             "pass 'model' or 'node' to pick the target"}
        path = body["model_path"]
        params = _load_model_path(targets[0].engine.spec, path)
        if params is None:
            return 400, {"error": f"no loadable weights at '{path}'"}
        outcomes, ok = [], True
        for w in targets:
            try:
                outcomes.append(w.apply_weights(params, source=path))
            except Exception as exc:
                ok = False
                outcomes.append({"ok": False, "node_id": w.node_id,
                                 "error": str(exc)[:300]})
        return (200 if ok else 500), {"ok": ok, "reloaded": outcomes}

    routes[("POST", "/admin/reload")] = _admin_reload
    routes[("POST", "/score")] = (
        lambda body: (200, gateway.route_score(body)))
    # Observability plane: SLO burn over the merged lane histograms
    # (combined mode sees every lane's live TTFT/ITL windows) and the
    # merged cross-lane stitch (in-process fragments, no HTTP hop).
    routes[("GET", "/admin/slo")] = lambda _b: (
        200, gateway.slo_status(_named_hists())
        or {"error": "no objectives configured "
                     "(set --slo-ttft-p99-ms / --slo-itl-p99-ms / "
                     "--slo-completion-p99-ms)"})
    prefix_routes = {("GET", "/admin/trace/"): (
        lambda _b, rid: (200, gateway.stitched_trace(rid)))}

    server = _make_front_server(port, routes, workers, gateway, native_front,
                                prefix_routes=prefix_routes)
    kind = "native C++ front" if not isinstance(server, JsonHttpServer) else "python front"
    topo = (f"mesh {dict(mesh.shape)}" if mesh is not None
            else f"{n_lanes} lanes over {len(devices)} device(s)")
    print(f"tpu_engine combined serving: {topo}, port {port} ({kind})")
    if isinstance(server, JsonHttpServer):
        server.start(background=background)
    elif not background:
        import time as _time

        try:
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return gateway, workers, server


def _make_front_server(port: int, routes: dict, workers, gateway,
                       native_front: Optional[bool],
                       prefix_routes: Optional[dict] = None):
    """Choose the serving edge: the C++ HttpFront (cache hits answered
    without the GIL; misses + misc routes fall back to Python) when the
    native lib and raw-mode lane caches are available, else the Python
    ThreadingHTTPServer. native_front: None=auto, True=require, False=off.

    Multi-model deployments always use the Python front: the C++ hit path
    rings request_ids over ALL lanes with input-bytes cache keys — no
    model awareness — so it could answer a {"model": "gpt2"} request with
    an mlp lane's cached fragment. Silent wrong-model output beats any
    hit-path speedup; extend the C++ key schema before re-enabling."""
    models = {getattr(w.engine.spec, "name", None) for w in workers}
    if len(models) > 1:
        if native_front is True:
            raise RuntimeError(
                "native front is single-model (its ring and cache keys "
                "carry no model); serve multi-model with the python front")
        native_front = False
    use_native = False
    if native_front is not False:
        try:
            from tpu_engine.core import native

            use_native = native.available() and all(
                isinstance(w.cache, native.NativeLRUCache)
                and getattr(w.cache, "_raw", False) for w in workers)
        except Exception:
            use_native = False
        if native_front is True and not use_native:
            raise RuntimeError("native front requested but libtpucore.so or "
                               "raw-mode lane caches are unavailable")
    if not use_native:
        server = JsonHttpServer(port)
        for (method, path), handler in routes.items():
            server.route(method, path, handler)
        for (method, prefix), handler in (prefix_routes or {}).items():
            server.route_prefix(method, prefix, handler)
        return server

    import json as _json

    from tpu_engine.core.native import NativeHttpFront

    def fallback(method: str, path: str, body: bytes):
        handler = routes.get((method, path))
        if handler is None and prefix_routes:
            # Prefix routes (e.g. /admin/trace/<request_id>): same
            # longest-prefix-wins contract as JsonHttpServer.
            for (m, prefix), ph in sorted(prefix_routes.items(),
                                          key=lambda kv: -len(kv[0][1])):
                if m == method and path.startswith(prefix) \
                        and len(path) > len(prefix):
                    suffix = path[len(prefix):]
                    handler = (lambda body, _h=ph, _s=suffix:
                               _h(body, _s))
                    break
        if handler is None:
            return 404, _json.dumps({"error": f"no route {method} {path}"}).encode()
        try:
            parsed = _json.loads(body) if method == "POST" else None
            result = handler(parsed)
            # (status, payload) or (status, payload, content_type); the
            # content type rides through tpu_front_reply2 so /metrics is
            # text/plain even behind the C++ front (Prometheus 3.x rejects
            # scrapes served as application/json).
            ctype = result[2] if len(result) == 3 else None
            status, payload = result[0], result[1]
            if not isinstance(payload, (bytes, bytearray)):
                if (hasattr(payload, "__iter__")
                        and not isinstance(payload, (dict, list, str))):
                    # SSE iterator (/generate/stream): the C++ front
                    # replies with one complete buffer, so the events ship
                    # as a single SSE-formatted body — same wire contract,
                    # no incremental flush (use the python front or a
                    # worker port for true streaming granularity). Drained
                    # INSIDE this try: an iterator error must become a
                    # 500 response, never escape into the C++ callback.
                    payload = b"".join(payload)
                else:
                    payload = _json.dumps(payload).encode()
        except ShedError as exc:
            # Resilience refusal (deadline/overload/drain): 503 with the
            # machine-readable kind. (The C++ reply path carries no extra
            # headers, so Retry-After rides only the Python front.)
            return 503, _json.dumps({"error": str(exc),
                                     "kind": exc.kind}).encode()
        except (KeyError, ValueError, TypeError) as exc:
            return 400, _json.dumps({"error": str(exc)}).encode()
        except Exception as exc:
            return 500, _json.dumps({"error": str(exc)}).encode()
        if ctype is not None:
            return status, payload, ctype
        return status, payload

    front = NativeHttpFront(port, fallback)
    for w in workers:
        front.add_lane(w.node_id, w.cache, gateway.breaker_for(w.node_id))
        w.external_counters = (lambda name=w.node_id: front.lane_counters(name))
        w.on_fault_change(lambda healthy, name=w.node_id:
                          front.set_lane_enabled(name, healthy))
    front.start()
    return front


def _print_worker_banner(worker: WorkerNode, config: WorkerConfig) -> None:
    # Startup banner parity (reference worker_node.cpp:192-201).
    bar = "━" * 44
    print(bar)
    print(f"Worker Node: {config.node_id}")
    print(bar)
    print(f"   Port:              {config.port}")
    print(f"   Model:             {worker.engine.spec.name}")
    print(f"   Cache Capacity:    {config.cache_capacity} entries")
    print(f"   Batch Size:        {config.max_batch_size} requests")
    print(f"   Batch Timeout:     {int(config.batch_timeout_ms)}ms")
    print(bar)
    print("Ready to accept requests!")
