"""Worker clients: how the gateway reaches a serving lane.

The reference gateway holds one persistent ``httplib::Client`` per worker
(``/root/reference/src/gateway.cpp:29-33``). Here the dispatch target is
pluggable:

- ``LocalWorkerClient`` — the TPU-native shape: the lane lives in the same
  process (one process owns all chips; "routing" selects a lane, no HTTP
  hop, no JSON re-encode).
- ``HttpWorkerClient`` — the reference deployment shape: POST /infer over
  a persistent connection pool with the reference's 5 s timeouts, enabling
  multi-host (DCN) topologies and wire-compat testing.
"""

from __future__ import annotations

import http.client
import json
import queue
import socket
import threading
from typing import Optional, Tuple

from tpu_engine.utils.deadline import DeadlineExceeded, Overloaded, ShedError


class WorkerError(Exception):
    """Dispatch failure: connection error, timeout, non-200, device error."""


class LocalWorkerClient:
    def __init__(self, worker):
        self.worker = worker

    def infer(self, payload: dict) -> dict:
        try:
            return self.worker.handle_infer(payload)
        except (KeyError, TypeError, ValueError):
            # Malformed request — the worker would answer 500 over HTTP
            # (reference worker_node.cpp:180-186); treat equally here.
            raise
        except ShedError:
            # Policy refusal (deadline/overload/drain): the lane is healthy
            # — the gateway fails over (Overloaded) or stops (expired
            # deadline) WITHOUT a breaker penalty.
            raise
        except Exception as exc:  # device/runtime failure → breaker signal
            raise WorkerError(str(exc)) from exc

    def infer_raw(self, payload: dict) -> bytes:
        """Pre-serialized response bytes (worker splices its cached output
        fragment) — the combined server's hot path."""
        try:
            return self.worker.handle_infer_raw(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def generate(self, payload: dict) -> dict:
        try:
            return self.worker.handle_generate(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def score(self, payload: dict) -> dict:
        try:
            return self.worker.handle_score(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def generate_stream(self, payload: dict):
        """SSE event-chunk iterator (in-process: the worker's iterator
        passes straight through — no proxy buffering)."""
        try:
            return self.worker.handle_generate_stream(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def drain(self) -> dict:
        self.worker.drain()
        return {"ok": True, "node_id": self.worker.node_id,
                "draining": True}

    def health(self) -> dict:
        return self.worker.get_health()


def parse_worker_url(url: str, default_port: int = 8080) -> Tuple[str, int]:
    """'host', 'host:port', or 'http://host:port' → (host, port). Default
    port 8080 mirrors the reference's parseUrl (``gateway.cpp:139,147``)."""
    u = url.strip()
    if "://" in u:
        u = u.split("://", 1)[1]
    u = u.split("/", 1)[0]
    if ":" in u:
        host, port_s = u.rsplit(":", 1)
        return host, int(port_s)
    return u, default_port


class HttpWorkerClient:
    """Thread-safe persistent-connection pool to one worker."""

    def __init__(self, url: str, timeout_s: float = 5.0, default_port: int = 8080,
                 pool_size: int = 64, gen_timeout_s: float = 120.0):
        self.host, self.port = parse_worker_url(url, default_port)
        self.url = f"{self.host}:{self.port}"
        self._timeout = timeout_s
        # /generate holds the socket for a whole decode loop (+ first-call
        # XLA compile) — the reference's 5 s /infer timeout would misread
        # every realistic generation as a worker failure and trip breakers.
        self._gen_timeout = max(gen_timeout_s, timeout_s)
        self._pool: "queue.LifoQueue[Optional[http.client.HTTPConnection]]" = queue.LifoQueue()
        for _ in range(pool_size):
            self._pool.put(None)  # lazily created

    def _acquire(self) -> http.client.HTTPConnection:
        try:
            conn = self._pool.get(timeout=self._timeout)
        except queue.Empty:
            raise WorkerError(f"connection pool to {self.url} exhausted")
        if conn is None:
            try:
                conn = http.client.HTTPConnection(self.host, self.port, timeout=self._timeout)
                conn.connect()
                conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except Exception as exc:
                # Return the slot before surfacing the failure, else the pool
                # leaks one slot per dead-worker connect attempt.
                self._pool.put(None)
                raise WorkerError(f"worker {self.url}: {exc}") from exc
        return conn

    def _release(self, conn: Optional[http.client.HTTPConnection]) -> None:
        self._pool.put(conn)

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 timeout_s: Optional[float] = None) -> dict:
        out = self._request_raw(method, path, body, timeout_s)
        try:
            return json.loads(out)
        except Exception as exc:
            raise WorkerError(f"worker {self.url}: bad response body: {exc}") from exc

    def _request_raw(self, method: str, path: str, body: Optional[dict] = None,
                     timeout_s: Optional[float] = None) -> bytes:
        conn = self._acquire()
        try:
            t = timeout_s if timeout_s is not None else self._timeout
            deadline_clamped = False
            if isinstance(body, dict) and body.get("deadline_ms") is not None:
                # Deadline propagation: never hold the socket meaningfully
                # past the request's remaining budget (+250 ms so the
                # worker's own 503 can arrive and be classified instead of
                # a generic timeout).
                budget = max(0.05, float(body["deadline_ms"]) / 1000.0 + 0.25)
                if budget < t:
                    t, deadline_clamped = budget, True
            conn.timeout = t
            if conn.sock is not None:
                conn.sock.settimeout(t)
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            if isinstance(body, dict) and isinstance(
                    body.get("traceparent"), str):
                # W3C trace propagation: mirror the payload's context as
                # the standard `traceparent` HTTP header so intermediaries
                # (proxies, meshes, non-tpu_engine collectors) see the
                # trace without parsing the JSON body.
                headers["traceparent"] = body["traceparent"]
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        except Exception as exc:
            conn.close()
            self._release(None)
            if deadline_clamped and isinstance(exc, (socket.timeout,
                                                     TimeoutError)):
                # The socket timed out because the CLIENT's budget ran out
                # — for THIS request that is terminal (DeadlineExceeded,
                # no failover: the budget is spent). But the lane HELD the
                # request past the budget without answering, which is also
                # the signature of a hang: mark the exception lane_suspect
                # so the gateway still feeds the breaker. Consecutive-
                # failure breakers self-correct on any within-budget
                # success (cache hits), so only a lane that NEVER answers
                # inside client budgets accrues enough to OPEN — which is
                # precisely a lane traffic should leave.
                shed = DeadlineExceeded(
                    f"worker {self.url}: deadline expired awaiting "
                    "response")
                shed.lane_suspect = True
                raise shed from exc
            raise WorkerError(f"worker {self.url}: {exc}") from exc
        if 400 <= resp.status < 500:
            # Client error (bad payload, unsupported op): the request is at
            # fault, not the worker — don't feed the breaker. Connection is
            # still good (response fully read).
            detail = ""
            try:
                detail = json.loads(data).get("error", "")
            except Exception:
                pass
            self._release(conn)
            raise ValueError(
                f"worker {self.url} rejected request ({resp.status}): {detail}")
        if resp.status == 503:
            # Resilience shed: mirror the in-process exception types so the
            # gateway treats a remote lane exactly like a local one (fail
            # over on overload/drain, stop on an expired deadline — no
            # breaker penalty either way). An unclassifiable 503 (a dying
            # proxy, a non-resilience server) stays a WorkerError below.
            kind = None
            try:
                kind = json.loads(data).get("kind")
            except Exception:
                pass
            if kind in ("overloaded", "deadline_exceeded"):
                self._release(conn)  # response fully read; conn healthy
                exc_cls = (Overloaded if kind == "overloaded"
                           else DeadlineExceeded)
                raise exc_cls(f"worker {self.url} shed request ({kind})")
        if resp.status != 200:
            conn.close()
            self._release(None)
            raise WorkerError(f"worker {self.url} returned {resp.status}")
        self._release(conn)
        return data

    def infer(self, payload: dict) -> dict:
        return self._request("POST", "/infer", payload)

    def infer_raw(self, payload: dict) -> bytes:
        """Raw response bytes, not parsed: the gateway proxies them verbatim
        (the reference pays a parse + re-encode per hop, gateway.cpp:99-103)."""
        return self._request_raw("POST", "/infer", payload)

    def generate(self, payload: dict) -> dict:
        return self._request("POST", "/generate", payload,
                             timeout_s=self._gen_timeout)

    def score(self, payload: dict) -> dict:
        return self._request("POST", "/score", payload,
                             timeout_s=self._gen_timeout)

    def generate_stream(self, payload: dict):
        """Streaming across an HTTP hop degrades to one terminal event
        (the blocking /generate result re-framed as SSE): multi-host
        deployments keep the wire contract; per-chunk streaming granularity
        is a combined-mode (in-process lane) property."""
        from tpu_engine.serving.http import sse_event

        result = self.generate(payload)

        def events():
            yield sse_event({"tokens": result["tokens"]})
            yield sse_event({"done": True, **result})
        return events()

    def drain(self) -> dict:
        return self._request("POST", "/admin/drain", {"action": "drain"})

    def health(self) -> dict:
        return self._request("GET", "/health")
