"""Worker clients: how the gateway reaches a serving lane.

The reference gateway holds one persistent ``httplib::Client`` per worker
(``/root/reference/src/gateway.cpp:29-33``). Here the dispatch target is
pluggable:

- ``LocalWorkerClient`` — the TPU-native shape: the lane lives in the same
  process (one process owns all chips; "routing" selects a lane, no HTTP
  hop, no JSON re-encode).
- ``HttpWorkerClient`` — the reference deployment shape: POST /infer over
  a persistent connection pool with the reference's 5 s timeouts, enabling
  multi-host (DCN) topologies and wire-compat testing.
"""

from __future__ import annotations

import http.client
import json
import queue
import socket
import threading
from typing import Optional, Tuple

from tpu_engine.utils.deadline import DeadlineExceeded, Overloaded, ShedError


class WorkerError(Exception):
    """Dispatch failure: connection error, timeout, non-200, device error."""


class LocalWorkerClient:
    def __init__(self, worker):
        self.worker = worker

    def infer(self, payload: dict) -> dict:
        try:
            return self.worker.handle_infer(payload)
        except (KeyError, TypeError, ValueError):
            # Malformed request — the worker would answer 500 over HTTP
            # (reference worker_node.cpp:180-186); treat equally here.
            raise
        except ShedError:
            # Policy refusal (deadline/overload/drain): the lane is healthy
            # — the gateway fails over (Overloaded) or stops (expired
            # deadline) WITHOUT a breaker penalty.
            raise
        except Exception as exc:  # device/runtime failure → breaker signal
            raise WorkerError(str(exc)) from exc

    def infer_raw(self, payload: dict) -> bytes:
        """Pre-serialized response bytes (worker splices its cached output
        fragment) — the combined server's hot path."""
        try:
            return self.worker.handle_infer_raw(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def generate(self, payload: dict) -> dict:
        try:
            return self.worker.handle_generate(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def score(self, payload: dict) -> dict:
        try:
            return self.worker.handle_score(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def generate_stream(self, payload: dict):
        """SSE event-chunk iterator (in-process: the worker's iterator
        passes straight through — no proxy buffering)."""
        try:
            return self.worker.handle_generate_stream(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def drain(self) -> dict:
        status = self.worker.drain()
        return {"ok": True, "node_id": self.worker.node_id,
                "draining": True, "status": status}

    def undrain(self) -> dict:
        status = self.worker.undrain()
        return {"ok": True, "node_id": self.worker.node_id,
                "draining": False, "status": status}

    def set_role(self, role: str) -> dict:
        """Flip the lane's serving role (disaggregated serving; the
        gateway's set_worker_role drives this around a drain+migrate)."""
        try:
            return self.worker.set_role(role)
        except (KeyError, TypeError, ValueError):
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def migrate(self, payload: dict, timeout_s: Optional[float] = None) -> dict:
        """Export one live stream's row for migration (in-process: the
        worker's quiesce-and-snapshot runs directly; ``timeout_s`` rides
        in the payload for the scheduler's export wait)."""
        if timeout_s is not None:
            payload = {**payload, "timeout_s": timeout_s}
        try:
            return self.worker.handle_migrate_export(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def export_prefix(self, payload: dict,
                      timeout_s: Optional[float] = None) -> dict:
        """Pull the longest cached radix chain matching a token prefix
        (fleet prefix tier; in-process: the worker serializes under its
        pool lock directly — refusals come back ``ok=False``, never as
        exceptions)."""
        try:
            return self.worker.handle_export_prefix(payload)
        except (KeyError, TypeError, ValueError):
            raise
        except ShedError:
            raise
        except Exception as exc:
            raise WorkerError(str(exc)) from exc

    def health(self) -> dict:
        return self.worker.get_health()

    def trace_spans(self) -> list:
        """The lane's span-ring snapshot (recorder schema) — the
        gateway's /admin/trace stitcher pulls fragments through this."""
        return self.worker.tracer.snapshot()

    def flight_dump(self, reason: str):
        """Force a flight-recorder postmortem dump on the lane (None
        when the lane runs no recorder)."""
        return self.worker.flight_dump(reason)


def parse_worker_url(url: str, default_port: int = 8080) -> Tuple[str, int]:
    """'host', 'host:port', or 'http://host:port' → (host, port). Default
    port 8080 mirrors the reference's parseUrl (``gateway.cpp:139,147``)."""
    u = url.strip()
    if "://" in u:
        u = u.split("://", 1)[1]
    u = u.split("/", 1)[0]
    if ":" in u:
        host, port_s = u.rsplit(":", 1)
        return host, int(port_s)
    return u, default_port


class HttpWorkerClient:
    """Thread-safe persistent-connection pool to one worker."""

    def __init__(self, url: str, timeout_s: float = 5.0, default_port: int = 8080,
                 pool_size: int = 64, gen_timeout_s: float = 120.0):
        self.host, self.port = parse_worker_url(url, default_port)
        self.url = f"{self.host}:{self.port}"
        self._timeout = timeout_s
        # /generate holds the socket for a whole decode loop (+ first-call
        # XLA compile) — the reference's 5 s /infer timeout would misread
        # every realistic generation as a worker failure and trip breakers.
        self._gen_timeout = max(gen_timeout_s, timeout_s)
        self._pool: "queue.LifoQueue[Optional[http.client.HTTPConnection]]" = queue.LifoQueue()
        for _ in range(pool_size):
            self._pool.put(None)  # lazily created

    def _acquire(self) -> http.client.HTTPConnection:
        try:
            conn = self._pool.get(timeout=self._timeout)
        except queue.Empty:
            raise WorkerError(f"connection pool to {self.url} exhausted")
        if conn is None:
            try:
                conn = http.client.HTTPConnection(self.host, self.port, timeout=self._timeout)
                conn.connect()
                conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except Exception as exc:
                # Return the slot before surfacing the failure, else the pool
                # leaks one slot per dead-worker connect attempt.
                self._pool.put(None)
                raise WorkerError(f"worker {self.url}: {exc}") from exc
        return conn

    def _release(self, conn: Optional[http.client.HTTPConnection]) -> None:
        self._pool.put(conn)

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 timeout_s: Optional[float] = None) -> dict:
        out = self._request_raw(method, path, body, timeout_s)
        try:
            return json.loads(out)
        except Exception as exc:
            raise WorkerError(f"worker {self.url}: bad response body: {exc}") from exc

    def _request_raw(self, method: str, path: str, body: Optional[dict] = None,
                     timeout_s: Optional[float] = None) -> bytes:
        conn = self._acquire()
        try:
            t = timeout_s if timeout_s is not None else self._timeout
            deadline_clamped = False
            if isinstance(body, dict) and body.get("deadline_ms") is not None:
                # Deadline propagation: never hold the socket meaningfully
                # past the request's remaining budget (+250 ms so the
                # worker's own 503 can arrive and be classified instead of
                # a generic timeout).
                budget = max(0.05, float(body["deadline_ms"]) / 1000.0 + 0.25)
                if budget < t:
                    t, deadline_clamped = budget, True
            conn.timeout = t
            if conn.sock is not None:
                conn.sock.settimeout(t)
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            if isinstance(body, dict) and isinstance(
                    body.get("traceparent"), str):
                # W3C trace propagation: mirror the payload's context as
                # the standard `traceparent` HTTP header so intermediaries
                # (proxies, meshes, non-tpu_engine collectors) see the
                # trace without parsing the JSON body.
                headers["traceparent"] = body["traceparent"]
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        except Exception as exc:
            conn.close()
            self._release(None)
            raise self._transport_error(exc, deadline_clamped) from exc
        if resp.status != 200:
            raise self._classify_error_response(conn, resp.status, data)
        self._release(conn)
        return data

    def _transport_error(self, exc: BaseException,
                         deadline_clamped: bool) -> Exception:
        """Transport-layer failure -> the exception to raise (one
        classification shared by the blocking and streaming paths). A
        socket timeout under a deadline-clamped read means the CLIENT's
        budget ran out — for THIS request that is terminal
        (DeadlineExceeded, no failover: the budget is spent). But the
        lane HELD the request past the budget without answering, which
        is also the signature of a hang: mark the exception lane_suspect
        so the gateway still feeds the breaker. Consecutive-failure
        breakers self-correct on any within-budget success, so only a
        lane that NEVER answers inside client budgets accrues enough to
        OPEN — which is precisely a lane traffic should leave. Anything
        else is a lane fault (WorkerError -> breaker + failover)."""
        if deadline_clamped and isinstance(exc, (socket.timeout,
                                                 TimeoutError)):
            shed = DeadlineExceeded(
                f"worker {self.url}: deadline expired awaiting response")
            shed.lane_suspect = True
            return shed
        return WorkerError(f"worker {self.url}: {exc}")

    def _classify_error_response(self, conn, status: int,
                                 data: bytes) -> Exception:
        """Non-200 response -> the exception to raise, with one breaker/
        pool semantics shared by the blocking and streaming paths: 4xx =
        the request's fault (ValueError, conn still healthy — response
        fully read); a classified 503 shed mirrors the in-process
        exception types so the gateway treats a remote lane exactly like
        a local one (fail over on overload/drain, stop on an expired
        deadline — no breaker penalty either way); anything else is a
        WorkerError with the conn closed (an unclassifiable 503 — a
        dying proxy, a non-resilience server — lands here too)."""
        if 400 <= status < 500:
            detail = ""
            try:
                detail = json.loads(data).get("error", "")
            except Exception:
                pass
            self._release(conn)
            return ValueError(
                f"worker {self.url} rejected request ({status}): {detail}")
        if status == 503:
            kind = None
            try:
                kind = json.loads(data).get("kind")
            except Exception:
                pass
            if kind in ("overloaded", "deadline_exceeded"):
                self._release(conn)  # response fully read; conn healthy
                exc_cls = (Overloaded if kind == "overloaded"
                           else DeadlineExceeded)
                return exc_cls(f"worker {self.url} shed request ({kind})")
        conn.close()
        self._release(None)
        return WorkerError(f"worker {self.url} returned {status}")

    def infer(self, payload: dict) -> dict:
        return self._request("POST", "/infer", payload)

    def infer_raw(self, payload: dict) -> bytes:
        """Raw response bytes, not parsed: the gateway proxies them verbatim
        (the reference pays a parse + re-encode per hop, gateway.cpp:99-103)."""
        return self._request_raw("POST", "/infer", payload)

    def generate(self, payload: dict) -> dict:
        return self._request("POST", "/generate", payload,
                             timeout_s=self._gen_timeout)

    def score(self, payload: dict) -> dict:
        return self._request("POST", "/score", payload,
                             timeout_s=self._gen_timeout)

    def generate_stream(self, payload: dict):
        """TRUE streaming across the HTTP hop: POST /generate/stream on
        the worker and yield each SSE frame as it arrives over the
        chunked response. A gateway in front of remote workers now sees
        tokens at the same granularity as an in-process lane — which is
        what lets its crash-tolerant stream journal resume a mid-stream
        worker death from the exact relayed prefix. (Previously this
        degraded to the blocking /generate re-framed as one terminal
        event; the event schema is unchanged, only the delivery
        granularity improved.)

        Error contract: admission failures (connect error, 4xx, shed
        503) raise HERE, before the iterator is handed back — the same
        classification as ``_request_raw``, so breaker accounting and
        failover at iterator creation still work. A transport failure
        MID-stream raises ``WorkerError`` from the iterator; a premature
        EOF (worker killed between frames) simply ends the iteration
        without a terminal ``done`` event — the consumer must treat a
        truncated stream as a failure."""
        conn = self._acquire()
        t = self._gen_timeout
        deadline_clamped = False
        if isinstance(payload, dict) and payload.get("deadline_ms") is not None:
            # Same deadline clamp as _request_raw: frames arrive per
            # decode chunk, so the per-read timeout only needs to cover
            # the remaining budget (+ slack for the worker's own 503).
            budget = max(0.05, float(payload["deadline_ms"]) / 1000.0 + 0.25)
            if budget < t:
                t, deadline_clamped = budget, True
        try:
            conn.timeout = t
            if conn.sock is not None:
                conn.sock.settimeout(t)
            body = json.dumps(payload).encode()
            headers = {"Content-Type": "application/json"}
            if isinstance(payload.get("traceparent"), str):
                headers["traceparent"] = payload["traceparent"]
            conn.request("POST", "/generate/stream", body=body,
                         headers=headers)
            resp = conn.getresponse()
        except Exception as exc:
            conn.close()
            self._release(None)
            raise self._transport_error(exc, deadline_clamped) from exc
        if resp.status != 200:
            try:
                data = resp.read()
            except Exception:
                # The error BODY itself failed to read: the connection is
                # poisoned mid-response and must not rejoin the pool.
                conn.close()
                self._release(None)
                raise WorkerError(
                    f"worker {self.url} returned {resp.status} "
                    f"(error body unreadable)")
            raise self._classify_error_response(conn, resp.status, data)

        def frames():
            clean = False
            try:
                buf = b""
                while True:
                    line = resp.readline()  # chunked decode is transparent
                    if not line:
                        break  # end of response body
                    buf += line
                    if buf.endswith(b"\n\n"):
                        yield buf
                        buf = b""
                # A dangling partial frame means the body was truncated
                # MID-event (sse_event always terminates with a blank
                # line): drop it — an unterminated SSE frame can only
                # corrupt the consumer's parse (and a failover splice
                # must resume from the last COMPLETE event) — and treat
                # the connection as dirty, not reusable.
                clean = not buf
            except Exception as exc:
                # Transport death mid-stream (ConnectionReset,
                # IncompleteRead on an aborted chunked body): a lane
                # fault the consumer can fail over — EXCEPT a timeout
                # under a deadline-clamped read, which is the client's
                # own budget expiring (terminal, lane_suspect — same
                # classification as _request_raw).
                raise self._transport_error(exc, deadline_clamped) from exc
            finally:
                # `clean` distinguishes a fully-read body (keep-alive
                # connection reusable) from an error OR an abandoning
                # consumer (GeneratorExit lands here too): those must
                # close, or the pool slot would carry a poisoned conn.
                if clean:
                    self._release(conn)
                else:
                    conn.close()
                    self._release(None)
        return frames()

    def drain(self) -> dict:
        return self._request("POST", "/admin/drain", {"action": "drain"})

    def undrain(self) -> dict:
        return self._request("POST", "/admin/drain",
                             {"action": "undrain"})

    def set_role(self, role: str) -> dict:
        return self._request("POST", "/admin/role", {"role": role})

    def migrate(self, payload: dict,
                timeout_s: Optional[float] = None) -> dict:
        """POST /admin/migrate: export one live stream's row. The chain
        payload can be large and the export waits for a tick boundary,
        so the socket timeout is the caller's per-transfer budget (the
        generation timeout when none given)."""
        if timeout_s is not None:
            payload = {**payload, "timeout_s": max(0.5, timeout_s - 0.5)}
        return self._request("POST", "/admin/migrate", payload,
                             timeout_s=(timeout_s if timeout_s is not None
                                        else self._gen_timeout))

    def export_prefix(self, payload: dict,
                      timeout_s: Optional[float] = None) -> dict:
        """POST /admin/export_prefix: pull a peer lane's cached radix
        chain for a token prefix (fleet prefix tier). The chain payload
        scales with the prefix depth, so the socket timeout is the
        fetcher's per-fetch budget (--prefix-fetch-timeout)."""
        return self._request("POST", "/admin/export_prefix", payload,
                             timeout_s=timeout_s)

    def health(self) -> dict:
        return self._request("GET", "/health")

    def trace_spans(self) -> list:
        """The lane's spans reconstructed from GET /trace/export — the
        chrome "X" events round-trip back to recorder-snapshot schema
        (op/start/duration plus the tree ids riding in ``args``), which
        is all the gateway-side stitcher needs from a remote lane."""
        data = self._request("GET", "/trace/export")
        spans = []
        for ev in data.get("traceEvents") or []:
            if ev.get("ph") != "X":
                continue
            args = ev.get("args") or {}
            if args.get("evicted_parent"):
                continue  # synthetic root; re-synthesized at stitch time
            span = {
                "request_id": args.get("request_id"),
                "op": ev.get("name"),
                "node": self.url,
                "duration_us": int(ev.get("dur", 0)),
                "start_ts": float(ev.get("ts", 0)) / 1e6,
                "ts": (float(ev.get("ts", 0)) + ev.get("dur", 0)) / 1e6,
            }
            for k in ("trace_id", "span_id", "parent_id", "cached",
                      "batch_size"):
                if k in args:
                    span[k] = args[k]
            extra = {k: v for k, v in args.items()
                     if k not in span and k != "request_id"}
            if extra:
                span["attrs"] = extra
            spans.append(span)
        return spans

    def flight_dump(self, reason: str) -> dict:
        """Force a flight-recorder postmortem dump on the lane."""
        return self._request("POST", "/admin/timeline", {"dump": reason})

    def probe_health(self, timeout_s: float = 5.0) -> dict:
        """/health on a DEDICATED short-lived connection, bypassing the
        data pool: a lane whose pool slots are all held by long-lived
        streams is busy, not dead — the gateway's prober must never read
        pool exhaustion as `health_probe_failures` consecutive failures
        and eject its most-loaded healthy lane."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout_s)
        try:
            conn.request("GET", "/health")
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise WorkerError(
                    f"worker {self.url} /health returned {resp.status}")
            return json.loads(data)
        except WorkerError:
            raise
        except Exception as exc:
            raise WorkerError(f"worker {self.url}: {exc}") from exc
        finally:
            conn.close()
