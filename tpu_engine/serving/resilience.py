"""Resilience policies for the serving layer: retry budgets, backoff,
hedging quantiles, admission control, and the counters that make every
decision visible in ``/stats``.

The reference system's whole fault story is the circuit breaker plus
ring-order failover (``gateway.cpp:51-59``): correct for a DEAD lane,
useless for a SLOW one or a traffic spike. This module adds the missing
SRE-standard pieces:

- ``RetryBudget`` — a global cap tying retries to recent request volume
  (retries <= ratio * requests over a sliding window) so a failing fleet
  sees at most ``1 + ratio`` x amplification instead of N x from every
  request marching the whole ring.
- ``backoff_delay`` — exponential backoff with symmetric jitter, so
  retry waves decorrelate instead of synchronizing into thundering herds.
- ``LatencyTracker`` — a sliding-window latency quantile estimator that
  drives hedged dispatch ("fire a second lane when the primary exceeds
  p95").
- ``AdmissionController`` — worker-side bounded queue depth with
  deadline-aware early rejection and a drain (lame-duck) mode.
- ``ProbeStateMachine`` / ``FailoverCounters`` — the proactive lane
  health prober's eject/restore state machine and the stream-failover
  decision counters (DESIGN.md "Crash-tolerant streaming"): a breaker
  discovers a dead lane one victim request at a time, a prober in
  O(probe interval) for the whole fleet.

Every knob defaults to off/permissive (see ``GatewayConfig`` /
``WorkerConfig``): with defaults, behavior and wire schemas are
byte-identical to the breaker-only gateway.
"""

from __future__ import annotations

import bisect
import collections
import random
import threading
import time
from typing import Deque, Optional

from tpu_engine.utils.deadline import Deadline, DeadlineExceeded, Overloaded


def tier_cap(limit: int, frac: float) -> int:
    """THE tier-admission rule, defined once: a tier may occupy up to
    its fraction of the concurrency limit, floored at 1 slot so a tiny
    limit never zeroes a whole class outright (the full-limit check
    still rules). Shared by the worker AdmissionController below and
    the gateway's in-flight gauge (via overload.tier_limit) — the two
    layers must shed at the same thresholds for the same tier."""
    return max(1, int(limit * frac))


def backoff_delay(attempt: int, base_ms: float, max_ms: float,
                  jitter: float = 0.5,
                  rng: Optional[random.Random] = None) -> float:
    """Delay in SECONDS before retry number ``attempt`` (0-based):
    ``min(base * 2^attempt, max)`` spread symmetrically by ``jitter``
    (0.5 -> uniform in [0.5x, 1.5x]). ``base_ms == 0`` (the default)
    returns 0.0 — the reference's immediate ring-order failover."""
    if base_ms <= 0:
        return 0.0
    d_ms = min(float(base_ms) * (2.0 ** max(0, int(attempt))), float(max_ms))
    j = min(max(float(jitter), 0.0), 1.0)
    if j > 0:
        r = (rng or random).random()  # in [0, 1)
        d_ms *= 1.0 - j + 2.0 * j * r
    return d_ms / 1000.0


class RetryBudget:
    """Global retry budget: a retry is allowed while retries observed in
    the sliding window stay under ``ratio * requests + min_retries``.

    ``ratio=None`` disables the budget entirely (reference behavior:
    unlimited failover). ``min_retries`` keeps low-traffic deployments
    able to retry at all — a 10% budget of 3 requests rounds to zero.

    Thread-safe; O(1) amortized via timestamp deques.
    """

    def __init__(self, ratio: Optional[float], min_retries: int = 10,
                 window_s: float = 10.0):
        self.ratio = None if ratio is None else max(0.0, float(ratio))
        self.min_retries = max(0, int(min_retries))
        self.window_s = float(window_s)
        self._requests: Deque[float] = collections.deque()
        self._retries: Deque[float] = collections.deque()
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.ratio is not None

    def _gc(self, now: float) -> None:
        horizon = now - self.window_s
        for dq in (self._requests, self._retries):
            while dq and dq[0] < horizon:
                dq.popleft()

    def record_request(self) -> None:
        if self.ratio is None:
            return
        now = time.monotonic()
        with self._lock:
            self._gc(now)
            self._requests.append(now)

    def try_acquire(self) -> bool:
        """True (and records the retry) if the budget permits one more
        retry right now; False means the caller must NOT retry."""
        if self.ratio is None:
            return True
        now = time.monotonic()
        with self._lock:
            self._gc(now)
            allowed = self.ratio * len(self._requests) + self.min_retries
            if len(self._retries) + 1 > allowed:
                return False
            self._retries.append(now)
            return True

    def stats(self) -> dict:
        with self._lock:
            return {"window_requests": len(self._requests),
                    "window_retries": len(self._retries),
                    "ratio": self.ratio}


class LatencyTracker:
    """Sliding-window latency quantiles over the last ``window`` samples.
    Insertion keeps a sorted shadow list, so ``quantile`` is O(1) reads —
    at the default window (512) the O(log n) insert + O(n) delete is
    noise next to a single HTTP hop."""

    def __init__(self, window: int = 512):
        self.window = max(8, int(window))
        self._ring: Deque[float] = collections.deque()
        self._sorted: list = []
        self._lock = threading.Lock()

    def record(self, latency_s: float) -> None:
        v = float(latency_s)
        with self._lock:
            self._ring.append(v)
            bisect.insort(self._sorted, v)
            if len(self._ring) > self.window:
                old = self._ring.popleft()
                del self._sorted[bisect.bisect_left(self._sorted, old)]

    def __len__(self) -> int:
        return len(self._ring)

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile of the window, or None with no samples."""
        with self._lock:
            if not self._sorted:
                return None
            idx = min(len(self._sorted) - 1,
                      int(q * (len(self._sorted) - 1) + 0.5))
            return self._sorted[idx]


class ResilienceCounters:
    """Every resilience decision, counted. ``as_dict`` feeds the
    additive ``/stats`` ``resilience`` block and the Prometheus render;
    ``any_nonzero`` gates the block so a defaults-only deployment keeps
    its wire schema byte-identical to the breaker-only gateway."""

    FIELDS = ("deadline_rejected", "deadline_expired", "retries",
              "retry_budget_exhausted", "backoff_waits", "hedges",
              "hedge_wins", "hedge_losses", "shed_overloaded")

    def __init__(self):
        self._lock = threading.Lock()
        self._c = {f: 0 for f in self.FIELDS}

    def bump(self, field: str, n: int = 1) -> None:
        with self._lock:
            self._c[field] += n

    def get(self, field: str) -> int:
        with self._lock:
            return self._c[field]

    def any_nonzero(self) -> bool:
        with self._lock:
            return any(v for v in self._c.values())

    def as_dict(self) -> dict:
        with self._lock:
            return dict(self._c)


class FailoverCounters(ResilienceCounters):
    """Every crash-tolerant-streaming decision, counted — the additive
    ``/stats`` ``failover`` block and the ``tpu_engine_failover_*``
    Prometheus family. Each ``resumes_attempted`` / ``prober_*`` bump has
    a matching gateway span (``resume`` / ``prober``), and
    ``tools/fault_injection.py --crash`` asserts the two agree."""

    FIELDS = ("stream_failures", "resumes_attempted", "resumes_succeeded",
              "resumes_failed", "tokens_replayed", "prober_ejections",
              "prober_restores")


class MigrationCounters(ResilienceCounters):
    """Every live-stream-migration decision, counted — the additive
    ``/stats`` ``migration`` block and the ``tpu_engine_migration_*``
    Prometheus family. Decision fields pair 1:1 with gateway
    ``migration`` marker spans (``tools/fault_injection.py --migrate``
    asserts counters == spans); ``tokens_migrated`` is a value counter
    (tokens carried across a splice), span-free like
    ``tokens_replayed``. ``drain_failures`` counts graceful-drain calls
    that timed out or errored during ``remove_worker(drain=True)`` —
    removal proceeds anyway (a wedged lane must never hang membership
    changes)."""

    FIELDS = ("migrations_attempted", "streams_migrated",
              "migration_fallbacks", "export_refusals",
              "destination_unavailable", "import_dispatch_failed",
              "tokens_migrated", "drain_failures")

    SPAN_FIELDS = ("migrations_attempted", "streams_migrated",
                   "migration_fallbacks", "export_refusals",
                   "destination_unavailable", "import_dispatch_failed",
                   "drain_failures")


class HandoffCounters(ResilienceCounters):
    """Every disaggregated prefill→decode handoff decision, counted —
    the additive ``/stats`` ``handoff`` block and the
    ``tpu_engine_handoff_*`` Prometheus family. Decision fields pair
    1:1 with gateway ``kv_handoff`` marker spans
    (``tools/fault_injection.py --disagg`` asserts counters == spans);
    ``tokens_handed_off`` counts tokens carried across a handoff splice
    (value counter, span-free like ``tokens_replayed``).

    ``prefill_routed`` — fresh generate-class dispatches sent to a
    prefill-capable lane; ``prefill_unavailable`` — no admittable
    prefill lane, ring order took over (colocated on whatever lane).
    ``handoffs_attempted`` → then exactly one of ``handoffs_spliced``
    (decode lane adopted, zero re-prefilled tokens),
    ``export_refusals`` / ``destination_unavailable`` /
    ``dispatch_failed`` (handoff abandoned — the source row unparks and
    decodes locally, or the relay replays), or ``handoff_fallbacks``
    (the export landed but the splice did not — replay resume finished
    the stream). ``holds_cancelled`` — source holds released because no
    destination existed. ``role_flips`` — /admin/role rebalances."""

    FIELDS = ("prefill_routed", "prefill_unavailable",
              "handoffs_attempted", "handoffs_spliced",
              "export_refusals", "destination_unavailable",
              "dispatch_failed", "handoff_fallbacks", "holds_cancelled",
              "tokens_handed_off", "role_flips")

    SPAN_FIELDS = ("prefill_routed", "prefill_unavailable",
                   "handoffs_attempted", "handoffs_spliced",
                   "export_refusals", "destination_unavailable",
                   "dispatch_failed", "handoff_fallbacks",
                   "holds_cancelled", "role_flips")


class FleetCounters(ResilienceCounters):
    """Every elastic-fleet (autoscaler + /admin/fleet) decision, counted
    — the additive ``/stats`` ``fleet`` block and the
    ``tpu_engine_fleet_*`` Prometheus family. Every field pairs 1:1
    with a gateway ``fleet`` marker span
    (``tools/fault_injection.py --elastic`` asserts counters == spans).

    ``scale_up_attempted`` → exactly one of ``scale_up_completed`` (the
    new lane passed its /health probe and joined every ring) or
    ``scale_up_failed`` (no standby capacity, or the spawn never turned
    healthy inside ``autoscale_spawn_timeout_s`` — the fleet enters the
    named ``spawn-wedged`` degraded state and keeps serving unchanged).
    ``scale_down_attempted`` → ``scale_down_completed`` (drain +
    PR 11 stream migration landed cleanly) or ``scale_down_failed``
    (the drain leg wedged or the actuator timed out — membership still
    changes, journaled streams fall to the replay-resume rung, and the
    fleet enters ``drain-wedged``). ``rebalance_*`` mirror the same
    ladder for the prefill↔decode role-flip arm. ``decisions_held``
    counts actions the controller WANTED but suppressed (cooldown /
    min-max clamp / actuator already in flight) — idempotency made
    visible. ``degraded_entered`` / ``degraded_cleared`` bracket every
    named degraded-but-serving state."""

    FIELDS = ("scale_up_attempted", "scale_up_completed",
              "scale_up_failed", "scale_down_attempted",
              "scale_down_completed", "scale_down_failed",
              "rebalance_attempted", "rebalance_completed",
              "rebalance_failed", "decisions_held",
              "degraded_entered", "degraded_cleared")

    SPAN_FIELDS = FIELDS


class AffinityCounters(ResilienceCounters):
    """Every prefix-affinity routing decision, counted — the additive
    ``/stats`` ``affinity`` block and the ``tpu_engine_affinity_*``
    Prometheus family. ``affinity_routed`` dispatches went to the lane
    owning the prompt-prefix fingerprint; the ``*_fallbacks`` fields say
    why a request took ring order instead (the pre-affinity behavior):
    no block-aligned prefix to fingerprint, the affinity lane was
    ejected/broken, it was already running hotter than its ring peers
    by more than ``affinity_max_imbalance`` recent dispatches, or a
    stream resume just watched it die (``resume_skips``)."""

    FIELDS = ("affinity_routed", "no_fingerprint", "ejected_fallbacks",
              "imbalance_fallbacks", "resume_skips")


class PrefixDirCounters(ResilienceCounters):
    """Every fleet-prefix-directory decision, counted — the additive
    ``/stats`` ``prefix_directory`` block and the
    ``tpu_engine_prefix_dir_*`` Prometheus family. Decision fields pair
    1:1 with a gateway ``prefix_dir`` marker span
    (``tools/fault_injection.py --fleet-prefix`` asserts counters ==
    spans). ``seeded`` — prober /health sweeps that recorded at least
    one entry from a lane's radix summaries (one span per sweep, not
    per entry — probe cadence would drown the recorder); ``recorded``
    — post-completion updates (a lane just served this fingerprint, so
    it now owns the chain); ``invalidations`` — per-lane generation
    bumps (removal / drain / eject / recover) that voided entries;
    ``hints_attached`` — generate-class dispatches stamped with an
    owner hint; ``lookup_misses`` — fingerprinted dispatches the
    directory could not name a live owner for. ``evictions`` (LRU
    capacity drops) is a VALUE counter like ``tokens_replayed`` —
    span-free by design, excluded from SPAN_FIELDS."""

    FIELDS = ("seeded", "recorded", "evictions", "invalidations",
              "hints_attached", "lookup_misses")

    SPAN_FIELDS = ("seeded", "recorded", "invalidations",
                   "hints_attached", "lookup_misses")


class ProbeStateMachine:
    """Per-lane eject/restore state from a stream of probe outcomes:
    ``fail_threshold`` CONSECUTIVE failures eject a lane (once — repeat
    failures while ejected stay silent), any success restores an ejected
    lane and zeroes the failure run. Pure state, no threads: the gateway
    owns the probe loop, this owns the decisions (unit-testable)."""

    def __init__(self, fail_threshold: int = 3):
        self.fail_threshold = max(1, int(fail_threshold))
        self._fails: dict = {}     # lane -> consecutive probe failures
        self._ejected: set = set()
        self._lock = threading.Lock()

    def record(self, lane: str, ok: bool) -> Optional[str]:
        """Feed one probe outcome; returns "eject", "restore", or None."""
        with self._lock:
            if ok:
                self._fails[lane] = 0
                if lane in self._ejected:
                    self._ejected.discard(lane)
                    return "restore"
                return None
            n = self._fails.get(lane, 0) + 1
            self._fails[lane] = n
            if n >= self.fail_threshold and lane not in self._ejected:
                self._ejected.add(lane)
                return "eject"
            return None

    def ejected(self, lane: str) -> bool:
        with self._lock:
            return lane in self._ejected

    def forget(self, lane: str) -> None:
        """Drop a removed lane's state so a later lane reusing the name
        starts clean."""
        with self._lock:
            self._fails.pop(lane, None)
            self._ejected.discard(lane)


class AdmissionController:
    """Worker-side admission control: bounded in-flight depth,
    deadline-aware early rejection, and a drain (lame-duck) mode.

    ``max_depth=0`` (default) leaves depth unbounded — reference
    behavior. ``drain()`` flips the lane to refusing new admissions while
    in-flight work completes; ``/admin/drain`` and
    ``Gateway.remove_worker(drain=True)`` drive it.

    ``admit(deadline)`` raises ``Overloaded`` when draining or over depth
    and ``DeadlineExceeded`` when the deadline already passed; callers
    MUST pair a successful admit with ``release()``. ``check_deadline``
    adds the estimate-aware early rejection for the miss path.

    Overload-control extensions (serving/overload.py; both default off):
    ``tier_fracs`` switches on priority-tiered admission — tier t admits
    only while depth < fracs[t] * limit, so the lowest tier sheds first
    under pressure; ``limiter`` (an ``AIMDLimit``) replaces the static
    ``max_depth`` with the adaptive concurrency limit. Every
    overload-class shed still counts into ``shed_overloaded`` (the
    wire-compat total) AND into its per-cause field
    (``shed_depth`` / ``shed_tier`` / ``shed_adaptive``), and the raised
    ``Overloaded`` carries a ``cause`` attribute so upstream counters
    can attribute it without string matching.
    """

    def __init__(self, max_depth: int = 0, node_id: str = "?",
                 tier_fracs: Optional[tuple] = None, limiter=None):
        self.max_depth = max(0, int(max_depth))
        self.node_id = node_id
        self._tier_fracs = tier_fracs
        self.limiter = limiter
        self._depth = 0
        self._draining = False
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self.shed_overloaded = 0
        self.shed_deadline = 0
        self.shed_draining = 0
        # Per-cause split of shed_overloaded (the old total stays the
        # sum): static depth cap, priority-tier fraction, adaptive limit.
        self.shed_depth = 0
        self.shed_tier = 0
        self.shed_adaptive = 0

    # -- drain (lame-duck) ----------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> str:
        """Enter lame-duck mode. Idempotent with a NAMED status: the
        first call answers ``"draining"``, a repeat answers
        ``"already-draining"`` — a retried /admin/drain (operator
        double-submit, controller retry after a timed-out ack) must
        read as the no-op it is, never as an error."""
        with self._lock:
            if self._draining:
                return "already-draining"
            self._draining = True
            return "draining"

    def undrain(self) -> str:
        """Leave lame-duck mode. Idempotent with a NAMED status:
        ``"undrained"`` when a drain was actually lifted,
        ``"not-draining"`` when there was nothing to lift."""
        with self._lock:
            if not self._draining:
                return "not-draining"
            self._draining = False
            return "undrained"

    def wait_idle(self, timeout_s: float = 10.0) -> bool:
        """Block until in-flight work reaches zero (True) or the timeout
        passes (False) — the 'finishes in-flight work' half of drain."""
        deadline = time.monotonic() + timeout_s
        with self._idle:
            while self._depth > 0:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                self._idle.wait(timeout=rem)
            return True

    # -- admission ------------------------------------------------------------

    def effective_limit(self) -> int:
        """The concurrency limit currently in force: the adaptive
        limiter's when configured, else the static cap (0 = unbounded)."""
        if self.limiter is not None:
            return self.limiter.limit
        return self.max_depth

    def admit(self, deadline: Optional[Deadline] = None,
              tier: Optional[int] = None) -> None:
        """``tier``: the request's priority tier (highest = len(fracs)-1);
        None (or no tier_fracs configured) admits against the full limit
        — the pre-overload-control behavior."""
        limit = self.effective_limit()
        with self._lock:
            if self._draining:
                self.shed_draining += 1
                raise Overloaded(
                    f"lane {self.node_id} is draining (lame-duck)")
            if limit and self._depth >= limit:
                self.shed_overloaded += 1
                if self.limiter is not None:
                    self.shed_adaptive += 1
                    exc = Overloaded(
                        f"lane {self.node_id} at adaptive queue depth "
                        f"limit {limit}")
                    exc.cause = "adaptive"
                else:
                    self.shed_depth += 1
                    exc = Overloaded(
                        f"lane {self.node_id} at max queue depth "
                        f"{self.max_depth}")
                    exc.cause = "depth"
                raise exc
            if (limit and tier is not None and self._tier_fracs
                    and 0 <= tier < len(self._tier_fracs) - 1):
                # Below-top tiers admit only up to their fraction of the
                # limit (floored at 1 slot): lowest-tier-first shedding.
                cap = tier_cap(limit, self._tier_fracs[tier])
                if self._depth >= cap:
                    self.shed_overloaded += 1
                    self.shed_tier += 1
                    exc = Overloaded(
                        f"lane {self.node_id} shedding priority tier "
                        f"{tier} at depth {self._depth}/{limit}")
                    exc.cause = "tier"
                    raise exc
            if deadline is not None and deadline.expired():
                self.shed_deadline += 1
                raise DeadlineExceeded("deadline exceeded at admission")
            self._depth += 1

    def check_deadline(self, deadline: Optional[Deadline],
                       est_service_s: Optional[float] = None) -> None:
        """Early rejection for work about to enter a batch/decode lane —
        refusing doomed work here costs one cheap 503 instead of a batch
        row. Called on the MISS path (after the cache lookup) so a
        sub-millisecond cache hit is never shed against a miss-sized
        estimate.

        Two distinct refusals: an EXPIRED budget is DeadlineExceeded
        (terminal — no lane can help); a live budget this lane merely
        PREDICTS it cannot meet (remaining < service-time EWMA) is
        Overloaded — a lane-local judgment, so the gateway fails over
        (another lane may hold the result in ITS cache and answer in
        microseconds)."""
        if deadline is None:
            return
        rem = deadline.remaining_s()
        if rem <= 0:
            with self._lock:
                self.shed_deadline += 1
            raise DeadlineExceeded("deadline expired before dispatch")
        if est_service_s is not None and rem < est_service_s:
            with self._lock:
                self.shed_deadline += 1
            raise Overloaded(
                f"lane {self.node_id} cannot meet the deadline "
                f"(remaining {rem * 1e3:.0f} ms < estimated service "
                f"{est_service_s * 1e3:.0f} ms)")

    def release(self) -> None:
        with self._idle:
            self._depth = max(0, self._depth - 1)
            if self._depth == 0:
                self._idle.notify_all()

    @property
    def depth(self) -> int:
        return self._depth

    @property
    def active(self) -> bool:
        """Whether this controller has anything to report — gates the
        additive /health block (schema untouched at defaults)."""
        return bool(self.max_depth or self._draining or self.shed_overloaded
                    or self.shed_deadline or self.shed_draining
                    or self._tier_fracs is not None
                    or self.limiter is not None)

    def as_dict(self) -> dict:
        with self._lock:
            out = {
                "draining": self._draining,
                "queue_depth": self._depth,
                "max_queue_depth": self.max_depth,
                "shed_overloaded": self.shed_overloaded,
                "shed_deadline": self.shed_deadline,
                "shed_draining": self.shed_draining,
            }
            # Per-cause breakdown, additive and gated on the overload
            # features: a plain max_queue_depth deployment's /health
            # block keeps its exact pre-overload-control key set, and
            # shed_overloaded stays the sum of the causes either way.
            if self._tier_fracs is not None or self.limiter is not None:
                out["shed_depth"] = self.shed_depth
                out["shed_tier"] = self.shed_tier
                out["shed_adaptive"] = self.shed_adaptive
                if self.limiter is not None:
                    out["adaptive"] = self.limiter.as_dict()
            return out
