// tpu_engine native runtime core — C++17.
//
// TPU-native re-implementation of the runtime-side components that the
// reference system (AbhiramDodda/distributed-inference-engine-cpp) ships as
// C++: the LRU result cache (reference include/lru_cache.h), the FNV-1a
// consistent-hash ring (src/consistent_hash.cpp), the circuit breaker
// (src/circuit_breaker.cpp) and the dynamic batch queue
// (include/batch_processor.h). Same observable semantics, independent
// design: keys/values are opaque byte blobs (full-key hashing — no sampled
// VectorHash weakness), the ring exposes elastic add/remove, and the batch
// queue is a standalone MPMC structure whose timed batch-pop is called from
// the Python dispatch loop with the GIL released.
//
// Exposed to Python through the flat C API in core_api.cc (ctypes; pybind11
// is unavailable in this environment).

#ifndef TPU_ENGINE_NATIVE_CORE_H_
#define TPU_ENGINE_NATIVE_CORE_H_

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <list>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <utility>
#include <vector>

namespace tpucore {

// ---------------------------------------------------------------------------
// LruCache: mutex-guarded LRU over byte-blob keys and values.
// ---------------------------------------------------------------------------
class LruCache {
 public:
  explicit LruCache(std::size_t capacity) : capacity_(capacity) {}

  // Returns true and copies the value on hit; promotes the entry to MRU.
  // count_miss=false is for probe callers (the native HTTP front) whose
  // misses fall through to a second, counted Get on the Python path —
  // counting both would double every miss in the hit-rate stats.
  bool Get(const std::string& key, std::string* value_out,
           bool count_miss = true) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = index_.find(key);
    if (it == index_.end()) {
      if (count_miss) ++misses_;
      return false;
    }
    order_.splice(order_.begin(), order_, it->second);
    ++hits_;
    *value_out = it->second->second;
    return true;
  }

  void Put(const std::string& key, std::string value) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = index_.find(key);
    if (it != index_.end()) {
      it->second->second = std::move(value);
      order_.splice(order_.begin(), order_, it->second);
      return;
    }
    if (order_.size() >= capacity_ && !order_.empty()) {
      index_.erase(order_.back().first);
      order_.pop_back();
    }
    order_.emplace_front(key, std::move(value));
    index_[key] = order_.begin();
  }

  void Clear() {
    std::lock_guard<std::mutex> lk(mu_);
    order_.clear();
    index_.clear();
    hits_ = 0;
    misses_ = 0;
  }

  std::size_t Size() const {
    std::lock_guard<std::mutex> lk(mu_);
    return order_.size();
  }
  std::size_t capacity() const { return capacity_; }
  std::uint64_t hits() const {
    std::lock_guard<std::mutex> lk(mu_);
    return hits_;
  }
  std::uint64_t misses() const {
    std::lock_guard<std::mutex> lk(mu_);
    return misses_;
  }

 private:
  const std::size_t capacity_;
  mutable std::mutex mu_;
  // MRU at front. list<pair<key, value>> with an index into it.
  std::list<std::pair<std::string, std::string>> order_;
  std::unordered_map<std::string,
                     std::list<std::pair<std::string, std::string>>::iterator>
      index_;
  std::uint64_t hits_ = 0;
  std::uint64_t misses_ = 0;
};

// ---------------------------------------------------------------------------
// HashRing: FNV-1a/32 consistent hash with virtual nodes.
// Constants match the reference (src/consistent_hash.cpp:6-14) so request →
// lane assignment is bit-identical across the Python and native paths.
// ---------------------------------------------------------------------------
class HashRing {
 public:
  explicit HashRing(int virtual_nodes) : virtual_nodes_(virtual_nodes) {}

  static std::uint32_t Fnv1a(const std::string& key) {
    std::uint32_t h = 2166136261u;
    for (unsigned char c : key) {
      h ^= c;
      h *= 16777619u;
    }
    return h;
  }

  void AddNode(const std::string& node) {
    std::lock_guard<std::mutex> lk(mu_);
    for (int i = 0; i < virtual_nodes_; ++i) {
      ring_[Fnv1a(node + "#" + std::to_string(i))] = node;
    }
  }

  void RemoveNode(const std::string& node) {
    std::lock_guard<std::mutex> lk(mu_);
    for (int i = 0; i < virtual_nodes_; ++i) {
      auto it = ring_.find(Fnv1a(node + "#" + std::to_string(i)));
      if (it != ring_.end() && it->second == node) ring_.erase(it);
    }
  }

  // First vnode clockwise of hash(key), wrapping. Empty ring -> false.
  bool GetNode(const std::string& key, std::string* node_out) const {
    std::lock_guard<std::mutex> lk(mu_);
    if (ring_.empty()) return false;
    auto it = ring_.lower_bound(Fnv1a(key));
    if (it == ring_.end()) it = ring_.begin();
    *node_out = it->second;
    return true;
  }

  // Distinct nodes in ring order (failover order).
  std::vector<std::string> AllNodes() const {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<std::string> out;
    std::unordered_set<std::string> seen;
    for (const auto& kv : ring_) {
      if (seen.insert(kv.second).second) out.push_back(kv.second);
    }
    return out;
  }

  std::size_t NumNodes() const {
    std::lock_guard<std::mutex> lk(mu_);
    std::unordered_set<std::string> seen;
    for (const auto& kv : ring_) seen.insert(kv.second);
    return seen.size();
  }

 private:
  const int virtual_nodes_;
  mutable std::mutex mu_;
  std::map<std::uint32_t, std::string> ring_;
};

// ---------------------------------------------------------------------------
// Breaker: CLOSED -> OPEN -> HALF_OPEN machine, consecutive-failure
// semantics identical to the reference (src/circuit_breaker.cpp:12-47).
// ---------------------------------------------------------------------------
class Breaker {
 public:
  enum State : int { kClosed = 0, kOpen = 1, kHalfOpen = 2 };

  Breaker(int failure_threshold, int success_threshold, double timeout_s)
      : failure_threshold_(failure_threshold),
        success_threshold_(success_threshold),
        timeout_(timeout_s),
        last_failure_(Clock::now()) {}

  bool AllowRequest() {
    std::lock_guard<std::mutex> lk(mu_);
    if (state_ == kOpen) {
      if (std::chrono::duration<double>(Clock::now() - last_failure_).count() >=
          timeout_) {
        state_ = kHalfOpen;
        success_count_ = 0;
        return true;
      }
      return false;
    }
    return true;
  }

  void RecordSuccess() {
    std::lock_guard<std::mutex> lk(mu_);
    if (state_ == kHalfOpen) {
      if (++success_count_ >= success_threshold_) {
        state_ = kClosed;
        failure_count_ = 0;
      }
    } else {
      failure_count_ = 0;  // threshold counts *consecutive* failures
    }
  }

  void RecordFailure() {
    std::lock_guard<std::mutex> lk(mu_);
    ++failure_count_;
    last_failure_ = Clock::now();
    if (failure_count_ >= failure_threshold_ || state_ == kHalfOpen) {
      state_ = kOpen;
    }
  }

  int state() const {
    std::lock_guard<std::mutex> lk(mu_);
    return state_;
  }
  int failure_count() const {
    std::lock_guard<std::mutex> lk(mu_);
    return failure_count_;
  }
  int success_count() const {
    std::lock_guard<std::mutex> lk(mu_);
    return success_count_;
  }

 private:
  using Clock = std::chrono::steady_clock;
  const int failure_threshold_;
  const int success_threshold_;
  const double timeout_;
  mutable std::mutex mu_;
  State state_ = kClosed;
  int failure_count_ = 0;
  int success_count_ = 0;
  Clock::time_point last_failure_;
};

// ---------------------------------------------------------------------------
// BatchQueue: MPMC queue with a size-or-timeout timed batch pop.
//
// This is the native half of the dynamic batcher: producers (request
// handler threads) push byte-blob payloads and receive tickets; the
// dispatch loop calls PopBatch, which blocks until the queue is non-empty
// (reference wake semantics, batch_processor.h:105-129) or the timeout
// fires, then drains up to max_batch items. Response delivery (futures) is
// the caller's concern — this structure stays language-neutral.
// ---------------------------------------------------------------------------
class BatchQueue {
 public:
  struct Item {
    std::int64_t ticket;
    std::string payload;
  };

  BatchQueue(std::size_t max_batch, double timeout_s)
      : max_batch_(max_batch), timeout_(timeout_s) {}

  // Returns the ticket, or -1 if the queue is closed.
  std::int64_t Push(std::string payload) {
    std::int64_t ticket;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (closed_) return -1;
      ticket = next_ticket_++;
      queue_.push_back(Item{ticket, std::move(payload)});
    }
    cv_.notify_one();
    return ticket;
  }

  // Blocks until items are available or timeout. Fills `out` with up to
  // min(max_batch_, caller_max) items (caller_max=0 means max_batch_). Sets
  // *timed_out when the wait expired (the batch classification signal).
  // Returns false when closed and drained.
  bool PopBatch(std::vector<Item>* out, bool* timed_out,
                std::size_t caller_max = 0) {
    const std::size_t limit =
        caller_max ? std::min(caller_max, max_batch_) : max_batch_;
    std::unique_lock<std::mutex> lk(mu_);
    *timed_out = !cv_.wait_for(
        lk, std::chrono::duration<double>(timeout_),
        [this] { return !queue_.empty() || closed_; });
    if (queue_.empty() && closed_) return false;
    out->clear();
    while (!queue_.empty() && out->size() < limit) {
      out->push_back(std::move(queue_.front()));
      queue_.pop_front();
    }
    return true;
  }

  void Close() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      closed_ = true;
    }
    cv_.notify_all();
  }

  std::size_t Size() const {
    std::lock_guard<std::mutex> lk(mu_);
    return queue_.size();
  }

 private:
  const std::size_t max_batch_;
  const double timeout_;
  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Item> queue_;
  std::int64_t next_ticket_ = 0;
  bool closed_ = false;
};

}  // namespace tpucore

#endif  // TPU_ENGINE_NATIVE_CORE_H_
