// Native HTTP front door — C++17, no dependencies.
//
// The reference's serving edge is native (cpp-httplib thread-pool server,
// /root/reference/external/cpp-httplib via setup.sh:40-46); this is the
// TPU-native equivalent with the hot path pushed all the way down: a
// thread-per-connection HTTP/1.1 keep-alive server that answers /infer
// CACHE HITS entirely in C++ — FNV-1a ring lookup, LRU fetch of the
// pre-encoded output fragment, response splice — without ever touching the
// Python interpreter (no GIL). Misses, shaped requests, and every other
// route call back into Python (ctypes callback; ctypes acquires the GIL
// per call).
//
// Protocol subset: HTTP/1.1, Content-Length bodies only (no chunked),
// case-insensitive header match for Content-Length/Connection. The only
// clients on this socket are benchmark harnesses, curl, and
// http.client — all of which send Content-Length.

#ifndef TPU_ENGINE_NATIVE_HTTP_FRONT_H_
#define TPU_ENGINE_NATIVE_HTTP_FRONT_H_

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "core.h"

namespace tpucore {

// Filled by the Python fallback handler through tpu_front_reply(ctx, ...)
// or tpu_front_reply2(ctx, ..., content_type) — the latter carries a
// non-JSON content type (e.g. /metrics' Prometheus text exposition, which
// Prometheus 3.x refuses to scrape under application/json).
struct ReplySlot {
  int status = 500;
  std::string body = "{\"error\": \"python handler did not reply\"}";
  std::string content_type = "application/json";
};

// void handler(void* reply_ctx, method, path, body, body_len)
using PyHandler = void (*)(void*, const char*, const char*, const char*,
                           std::size_t);

class HttpFront {
 public:
  struct Lane {
    std::string name;
    LruCache* cache;                    // not owned (Python side owns)
    Breaker* breaker;                   // not owned; shared with the gateway
    std::atomic<bool> enabled{true};
    std::atomic<std::uint64_t> total{0};
    std::atomic<std::uint64_t> hits{0};
    Lane(std::string n, LruCache* c, Breaker* b)
        : name(std::move(n)), cache(c), breaker(b) {}
  };

  HttpFront(int port, int virtual_nodes, int fake_cached_latency_us)
      : ring_(virtual_nodes), fake_us_(fake_cached_latency_us), port_(port) {}

  ~HttpFront() { Stop(); }

  void AddLane(const std::string& name, LruCache* cache, Breaker* breaker) {
    std::lock_guard<std::mutex> lk(mu_);
    lanes_.push_back(std::make_unique<Lane>(name, cache, breaker));
    index_[name] = lanes_.back().get();
    ring_.AddNode(name);
  }

  void SetLaneEnabled(const std::string& name, bool enabled) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = index_.find(name);
    if (it != index_.end()) it->second->enabled.store(enabled);
  }

  void SetHandler(PyHandler h) { handler_ = h; }

  // Binds + starts the accept loop. Returns the bound port, or -1.
  int Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<std::uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return -1;
    }
    if (port_ == 0) {
      socklen_t alen = sizeof(addr);
      ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
      port_ = ntohs(addr.sin_port);
    }
    ::listen(listen_fd_, 1024);
    running_.store(true);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return port_;
  }

  void Stop() {
    bool was = running_.exchange(false);
    if (!was) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    if (accept_thread_.joinable()) accept_thread_.join();
    // Half-close live keep-alive connections so handler threads see EOF.
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    std::unordered_map<std::uint64_t, std::thread> rest;
    {
      std::lock_guard<std::mutex> lk(threads_mu_);
      rest.swap(conn_threads_);
    }
    for (auto& kv : rest) {
      if (kv.second.joinable()) kv.second.join();
    }
  }

  int port() const { return port_; }
  std::uint64_t LaneTotal(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = index_.find(name);
    return it == index_.end() ? 0 : it->second->total.load();
  }
  std::uint64_t LaneHits(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = index_.find(name);
    return it == index_.end() ? 0 : it->second->hits.load();
  }

 private:
  void AcceptLoop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (!running_.load()) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      ReapFinished();
      {
        std::lock_guard<std::mutex> lk(conn_mu_);
        conn_fds_.insert(fd);
      }
      std::uint64_t tid = next_thread_id_.fetch_add(1);
      std::thread t([this, fd, tid] {
        Serve(fd);
        // Self-registration on the done list is the ONLY cross-thread
        // signal; the accept loop joins exclusively ids found here, so it
        // never blocks on a thread still serving a live connection, and it
        // holds neither conn_mu_ nor threads_mu_ while joining.
        std::lock_guard<std::mutex> lk(done_mu_);
        done_ids_.push_back(tid);
      });
      {
        std::lock_guard<std::mutex> lk(threads_mu_);
        conn_threads_.emplace(tid, std::move(t));
      }
    }
  }

  // Joins only threads whose Serve() already returned. Join happens outside
  // every mutex: a joined thread's final act is the done-list append, so the
  // join can only wait on that last statement, never on live I/O.
  void ReapFinished() {
    std::vector<std::uint64_t> done;
    {
      std::lock_guard<std::mutex> lk(done_mu_);
      done.swap(done_ids_);
    }
    for (std::uint64_t tid : done) {
      std::thread t;
      {
        std::lock_guard<std::mutex> lk(threads_mu_);
        auto it = conn_threads_.find(tid);
        if (it == conn_threads_.end()) {
          // Finished before the accept loop emplaced it; retry next reap.
          std::lock_guard<std::mutex> dlk(done_mu_);
          done_ids_.push_back(tid);
          continue;
        }
        t = std::move(it->second);
        conn_threads_.erase(it);
      }
      if (t.joinable()) t.join();
    }
  }

  // Caps: a single header line (and the buffered remainder while looking for
  // one) may not exceed kMaxHeaderBytes (431), and a declared body may not
  // exceed kMaxBodyBytes (413); either way the connection is closed — without
  // this, one never-terminated or huge request exhausts server memory.
  static constexpr std::size_t kMaxHeaderBytes = 64 * 1024;
  static constexpr std::size_t kMaxBodyBytes = 64ull * 1024 * 1024;

  static bool ReadLine(int fd, std::string* buf, std::string* line) {
    // Reads from fd into *buf until a "\r\n" is available; pops it.
    for (;;) {
      auto pos = buf->find("\r\n");
      if (pos != std::string::npos) {
        *line = buf->substr(0, pos);
        buf->erase(0, pos + 2);
        return true;
      }
      if (buf->size() > kMaxHeaderBytes) return false;
      char tmp[4096];
      ssize_t n = ::recv(fd, tmp, sizeof(tmp), 0);
      if (n <= 0) return false;
      buf->append(tmp, static_cast<std::size_t>(n));
    }
  }

  static bool ReadN(int fd, std::string* buf, std::size_t n,
                    std::string* out) {
    while (buf->size() < n) {
      char tmp[8192];
      ssize_t r = ::recv(fd, tmp, sizeof(tmp), 0);
      if (r <= 0) return false;
      buf->append(tmp, static_cast<std::size_t>(r));
    }
    *out = buf->substr(0, n);
    buf->erase(0, n);
    return true;
  }

  static bool SendAll(int fd, const char* data, std::size_t len) {
    std::size_t off = 0;
    while (off < len) {
      ssize_t n = ::send(fd, data + off, len - off, MSG_NOSIGNAL);
      if (n <= 0) return false;
      off += static_cast<std::size_t>(n);
    }
    return true;
  }

  // Sends 431 before closing when a ReadLine failure was a header-size
  // overflow (vs a plain EOF/reset, where the peer is already gone).
  void MaybeReject431(int fd, const std::string& buf) {
    if (buf.size() > kMaxHeaderBytes) {
      std::string resp;
      WrapHttp(431, "{\"error\": \"request header too large\"}", &resp);
      SendAll(fd, resp.data(), resp.size());
    }
  }

  void Serve(int fd) {
    std::string buf;
    while (running_.load()) {
      std::string req_line;
      if (!ReadLine(fd, &buf, &req_line)) {
        MaybeReject431(fd, buf);
        break;
      }
      if (req_line.empty()) continue;
      auto sp1 = req_line.find(' ');
      auto sp2 = req_line.find(' ', sp1 + 1);
      if (sp1 == std::string::npos || sp2 == std::string::npos) break;
      std::string method = req_line.substr(0, sp1);
      std::string path = req_line.substr(sp1 + 1, sp2 - sp1 - 1);
      auto q = path.find('?');
      if (q != std::string::npos) path.erase(q);

      std::size_t content_length = 0;
      bool close_conn = false;
      std::string header;
      for (;;) {
        if (!ReadLine(fd, &buf, &header)) {
          MaybeReject431(fd, buf);
          return CloseFd(fd);
        }
        if (header.empty()) break;
        std::string lower;
        lower.reserve(header.size());
        for (char c : header) lower += static_cast<char>(std::tolower(c));
        if (lower.rfind("content-length:", 0) == 0) {
          content_length = std::strtoull(header.c_str() + 15, nullptr, 10);
        } else if (lower.rfind("connection:", 0) == 0 &&
                   lower.find("close") != std::string::npos) {
          close_conn = true;
        }
      }
      if (content_length > kMaxBodyBytes) {
        std::string resp;
        WrapHttp(413, "{\"error\": \"request body too large\"}", &resp);
        SendAll(fd, resp.data(), resp.size());
        return CloseFd(fd);
      }
      std::string body;
      if (content_length &&
          !ReadN(fd, &buf, content_length, &body)) {
        return CloseFd(fd);
      }

      std::string resp;
      if (method == "POST" && path == "/infer") {
        if (!TryInferHit(body, &resp)) PyFallback(method, path, body, &resp);
      } else {
        PyFallback(method, path, body, &resp);
      }
      if (!SendAll(fd, resp.data(), resp.size())) break;
      if (close_conn) break;
    }
    CloseFd(fd);
  }

  void CloseFd(int fd) {
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      conn_fds_.erase(fd);
    }
    ::close(fd);
  }

  // ---- /infer fast path -----------------------------------------------------

  // Extracts the JSON string value after `"key":`. Returns false on any
  // complexity (escapes, absence) — caller punts to Python.
  static bool JsonString(const std::string& body, const char* key,
                         std::string* out) {
    std::string pat = std::string("\"") + key + "\"";
    auto kpos = body.find(pat);
    if (kpos == std::string::npos) return false;
    auto colon = body.find(':', kpos + pat.size());
    if (colon == std::string::npos) return false;
    auto start = body.find('"', colon + 1);
    if (start == std::string::npos) return false;
    auto end = start + 1;
    while (end < body.size() && body[end] != '"') {
      if (body[end] == '\\') return false;  // escapes → Python
      ++end;
    }
    if (end >= body.size()) return false;
    *out = body.substr(start + 1, end - start - 1);
    return true;
  }

  // Parses the flat float array after `"input_data":` into f32 bytes
  // (bit-identical to numpy float32 conversion of the same doubles).
  static bool ParseInputKey(const std::string& body, std::string* key_out) {
    auto kpos = body.find("\"input_data\"");
    if (kpos == std::string::npos) return false;
    auto open = body.find('[', kpos);
    if (open == std::string::npos) return false;
    std::size_t i = open + 1;
    std::string key;
    key.reserve(64);
    for (;;) {
      while (i < body.size() &&
             (body[i] == ' ' || body[i] == ',' || body[i] == '\n' ||
              body[i] == '\t' || body[i] == '\r')) {
        ++i;
      }
      if (i >= body.size()) return false;
      if (body[i] == ']') break;
      if (body[i] == '[') return false;  // nested → Python
      char* endp = nullptr;
      double d = std::strtod(body.c_str() + i, &endp);
      if (endp == body.c_str() + i) return false;
      float f = static_cast<float>(d);
      key.append(reinterpret_cast<const char*>(&f), sizeof(f));
      i = static_cast<std::size_t>(endp - body.c_str());
    }
    *key_out = std::move(key);
    return true;
  }

  bool TryInferHit(const std::string& body, std::string* resp) {
    if (body.find("\"shape\"") != std::string::npos) return false;
    std::string rid;
    if (!JsonString(body, "request_id", &rid)) return false;
    std::string key;
    if (!ParseInputKey(body, &key)) return false;

    std::string node;
    if (!ring_.GetNode(rid, &node)) return false;
    Lane* lane = nullptr;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = index_.find(node);
      if (it == index_.end()) return false;
      lane = it->second;
    }
    if (!lane->enabled.load()) return false;
    // Shared-breaker gate: an OPEN lane must not serve even cached answers
    // from C++ (reference semantics: the gateway controls the probe), and
    // the hit below is a genuine success the breaker must observe — this is
    // how a healed lane's HALF_OPEN probes re-close through the hot path.
    if (lane->breaker != nullptr && !lane->breaker->AllowRequest()) {
      return false;  // Python gateway applies its own gate + failover.
    }
    std::string frag;
    if (!lane->cache->Get(key, &frag, /*count_miss=*/false)) {
      return false;  // Python path re-Gets and counts the miss there.
    }
    if (lane->breaker != nullptr) lane->breaker->RecordSuccess();
    lane->total.fetch_add(1);
    lane->hits.fetch_add(1);

    std::string payload;
    payload.reserve(frag.size() + rid.size() + 96);
    payload += "{\"request_id\": \"";
    payload += rid;
    payload += "\", \"output_data\": ";
    payload += frag;
    payload += ", \"node_id\": \"";
    payload += node;
    payload += "\", \"cached\": true, \"inference_time_us\": ";
    payload += std::to_string(fake_us_);
    payload += "}";
    WrapHttp(200, payload, resp);
    return true;
  }

  void PyFallback(const std::string& method, const std::string& path,
                  const std::string& body, std::string* resp) {
    ReplySlot slot;
    if (handler_ != nullptr) {
      handler_(&slot, method.c_str(), path.c_str(), body.data(), body.size());
    }
    WrapHttp(slot.status, slot.body, resp, slot.content_type.c_str());
  }

  static void WrapHttp(int status, const std::string& payload,
                       std::string* resp,
                       const char* content_type = "application/json") {
    const char* reason = status == 200 ? "OK"
                         : status == 400 ? "Bad Request"
                         : status == 404 ? "Not Found"
                         : status == 413 ? "Payload Too Large"
                         : status == 431 ? "Request Header Fields Too Large"
                                         : "Internal Server Error";
    resp->clear();
    resp->reserve(payload.size() + 160);
    *resp += "HTTP/1.1 ";
    *resp += std::to_string(status);
    *resp += " ";
    *resp += reason;
    *resp += "\r\nContent-Type: ";
    *resp += content_type;
    *resp += "\r\nContent-Length: ";
    *resp += std::to_string(payload.size());
    *resp += "\r\n\r\n";
    *resp += payload;
  }

  HashRing ring_;
  const int fake_us_;
  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  PyHandler handler_ = nullptr;
  std::thread accept_thread_;
  std::mutex mu_;
  std::vector<std::unique_ptr<Lane>> lanes_;
  std::unordered_map<std::string, Lane*> index_;
  std::mutex conn_mu_;
  std::unordered_set<int> conn_fds_;
  std::mutex threads_mu_;
  std::unordered_map<std::uint64_t, std::thread> conn_threads_;
  std::mutex done_mu_;
  std::vector<std::uint64_t> done_ids_;
  std::atomic<std::uint64_t> next_thread_id_{0};
};

}  // namespace tpucore

#endif  // TPU_ENGINE_NATIVE_HTTP_FRONT_H_
