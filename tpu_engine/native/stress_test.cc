// Concurrency stress test for the native core — built with -fsanitize=thread
// by tools/race_check.sh (race detection: the reference has no sanitizer
// story at all, SURVEY.md §5 — its CMake flags are plain -O3).
//
// Hammers every shared structure from many threads simultaneously:
//   LruCache   get/put/clear under contention (eviction + splice races)
//   HashRing   lookups during add/remove (elastic membership)
//   Breaker    allow/success/failure interleavings (state transitions)
//   BatchQueue producers racing a consumer's timed batch pops
//   HttpFront  hit-path counters vs lane enable/disable flips
// Exit 0 = no crashes; TSan reports go to stderr and fail the run.

#include <atomic>
#include <cstdio>
#include <string>
#include <thread>
#include <vector>

#include "core.h"
#include "http_front.h"

using namespace tpucore;

static constexpr int kThreads = 8;
static constexpr int kIters = 3000;

static void StressLru() {
  LruCache cache(64);
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&cache, t] {
      std::string val;
      for (int i = 0; i < kIters; ++i) {
        std::string key = "k" + std::to_string((t * 7 + i) % 128);
        if (i % 3 == 0) {
          cache.Put(key, "v" + std::to_string(i));
        } else if (i % 97 == 0) {
          cache.Clear();
        } else {
          cache.Get(key, &val);
        }
      }
    });
  }
  for (auto& t : ts) t.join();
  std::printf("lru ok (size=%zu hits=%llu misses=%llu)\n", cache.Size(),
              (unsigned long long)cache.hits(),
              (unsigned long long)cache.misses());
}

static void StressRing() {
  HashRing ring(50);
  ring.AddNode("a");
  ring.AddNode("b");
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&ring, t] {
      std::string node;
      for (int i = 0; i < kIters; ++i) {
        if (t == 0 && i % 200 == 0) {
          ring.RemoveNode("c");
          ring.AddNode("c");
        } else {
          ring.GetNode("key" + std::to_string(i), &node);
          if (i % 50 == 0) ring.AllNodes();
        }
      }
    });
  }
  for (auto& t : ts) t.join();
  std::printf("ring ok (%zu nodes)\n", ring.NumNodes());
}

static void StressBreaker() {
  Breaker b(5, 2, 0.001);
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&b, t] {
      for (int i = 0; i < kIters; ++i) {
        if (b.AllowRequest()) {
          if ((t + i) % 3 == 0) {
            b.RecordFailure();
          } else {
            b.RecordSuccess();
          }
        }
        b.state();
      }
    });
  }
  for (auto& t : ts) t.join();
  std::printf("breaker ok (state=%d)\n", b.state());
}

static void StressBatchQueue() {
  BatchQueue q(16, 0.001);
  std::atomic<long long> popped{0};
  std::thread consumer([&q, &popped] {
    std::vector<BatchQueue::Item> items;
    bool timed_out = false;
    while (q.PopBatch(&items, &timed_out)) {
      popped += (long long)items.size();
    }
  });
  std::vector<std::thread> producers;
  for (int t = 0; t < kThreads; ++t) {
    producers.emplace_back([&q] {
      for (int i = 0; i < kIters; ++i) q.Push("p" + std::to_string(i));
    });
  }
  for (auto& t : producers) t.join();
  q.Close();
  consumer.join();
  std::printf("batch queue ok (popped=%lld of %d)\n", popped.load(),
              kThreads * kIters);
  if (popped.load() != (long long)kThreads * kIters) std::abort();
}

static void StressFrontCounters() {
  // Exercises Lane atomics + shared cache + breaker the way the HTTP hit
  // path does, without sockets.
  LruCache cache(128);
  Breaker breaker(5, 2, 0.001);
  HttpFront front(0, 50, 50);
  front.AddLane("lane", &cache, &breaker);
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&, t] {
      std::string val;
      for (int i = 0; i < kIters; ++i) {
        std::string key = "k" + std::to_string(i % 64);
        if (i % 2 == 0) cache.Put(key, "[1.0]");
        cache.Get(key, &val, i % 3 == 0);
        if (t == 0 && i % 100 == 0) {
          front.SetLaneEnabled("lane", i % 200 == 0);
        }
        front.LaneTotal("lane");
      }
    });
  }
  for (auto& t : ts) t.join();
  std::printf("front counters ok\n");
}

int main() {
  StressLru();
  StressRing();
  StressBreaker();
  StressBatchQueue();
  StressFrontCounters();
  std::printf("ALL STRESS PASSED\n");
  return 0;
}
