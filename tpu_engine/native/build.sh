#!/usr/bin/env bash
# Minimal no-cmake build of libtpucore.so (used as the fallback by
# tpu_engine.core.native when the library has not been built yet).
set -euo pipefail
cd "$(dirname "$0")"
out="${1:-libtpucore.so}"
g++ -std=c++17 -O3 -Wall -Wextra -fPIC -shared -pthread core_api.cc -o "$out"
echo "built $out"
