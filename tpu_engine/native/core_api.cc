// Flat C API over the tpucore classes, consumed from Python via ctypes
// (tpu_engine/core/native.py). Ownership rules: every handle returned by a
// *_create is released by the matching *_destroy; byte buffers returned via
// tpu_alloc-ed pointers are released with tpu_free.

#include <clocale>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <locale.h>  // newlocale/uselocale (POSIX.1-2008)

#include "core.h"
#include "http_front.h"

using tpucore::BatchQueue;
using tpucore::Breaker;
using tpucore::HashRing;
using tpucore::HttpFront;
using tpucore::LruCache;
using tpucore::ReplySlot;

extern "C" {

// ----- shared ---------------------------------------------------------------

void tpu_free(void* p) { std::free(p); }

static char* AllocCopy(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() ? s.size() : 1));
  if (out && !s.empty()) std::memcpy(out, s.data(), s.size());
  return out;
}

// ----- fast JSON float encode ------------------------------------------------

// "[a,b,...]" with %.6g — six significant digits, the noise floor of the
// bf16 serving dtype (float32 responses keep ~1e-6 relative error, far
// inside every consumer's tolerance). json.dumps(list) costs ~700 us per
// 1000 floats under the GIL; this runs GIL-free (ctypes releases it) in
// ~tens of us, which matters because the reference's miss path pays float
// serialization per REQUEST (worker_node.cpp:75-82 builds the response
// JSON eagerly). Non-finite values spell NaN/Infinity/-Infinity exactly
// like Python's json.dumps so json.loads round-trips. Caller frees *out
// with tpu_free; returns the byte length.
std::size_t tpu_json_encode_f32(const float* data, std::size_t n,
                                char** out) {
  std::size_t cap = n * 16 + 3;  // "-3.40282e+38," is 13; 16 is safe
  char* buf = static_cast<char*>(std::malloc(cap));
  if (!buf) {
    *out = nullptr;
    return 0;
  }
  // snprintf honors LC_NUMERIC: a host locale with comma decimals would
  // emit "1,5" — which json.loads reads as TWO elements. Pin the C locale
  // for the whole encode (json.dumps, the path this replaces, is
  // locale-free).
  static locale_t c_loc = newlocale(LC_ALL_MASK, "C", nullptr);
  locale_t prior = uselocale(c_loc);
  std::size_t w = 0;
  buf[w++] = '[';
  for (std::size_t i = 0; i < n; ++i) {
    if (i) buf[w++] = ',';
    float v = data[i];
    if (std::isnan(v)) {
      std::memcpy(buf + w, "NaN", 3);
      w += 3;
    } else if (std::isinf(v)) {
      if (v < 0) {
        std::memcpy(buf + w, "-Infinity", 9);
        w += 9;
      } else {
        std::memcpy(buf + w, "Infinity", 8);
        w += 8;
      }
    } else {
      w += std::snprintf(buf + w, 17, "%.6g", static_cast<double>(v));
    }
  }
  buf[w++] = ']';
  uselocale(prior);
  *out = buf;
  return w;
}

// ----- LRU cache ------------------------------------------------------------

void* tpu_lru_create(std::size_t capacity) { return new LruCache(capacity); }
void tpu_lru_destroy(void* h) { delete static_cast<LruCache*>(h); }

// Returns 1 on hit (caller frees *val_out with tpu_free), 0 on miss.
int tpu_lru_get(void* h, const char* key, std::size_t klen, char** val_out,
                std::size_t* vlen_out) {
  std::string value;
  if (!static_cast<LruCache*>(h)->Get(std::string(key, klen), &value)) {
    return 0;
  }
  *val_out = AllocCopy(value);
  *vlen_out = value.size();
  return 1;
}

void tpu_lru_put(void* h, const char* key, std::size_t klen, const char* val,
                 std::size_t vlen) {
  static_cast<LruCache*>(h)->Put(std::string(key, klen),
                                 std::string(val, vlen));
}

void tpu_lru_clear(void* h) { static_cast<LruCache*>(h)->Clear(); }
std::size_t tpu_lru_size(void* h) { return static_cast<LruCache*>(h)->Size(); }
std::size_t tpu_lru_capacity(void* h) {
  return static_cast<LruCache*>(h)->capacity();
}
std::uint64_t tpu_lru_hits(void* h) { return static_cast<LruCache*>(h)->hits(); }
std::uint64_t tpu_lru_misses(void* h) {
  return static_cast<LruCache*>(h)->misses();
}

// ----- consistent-hash ring -------------------------------------------------

void* tpu_ring_create(int virtual_nodes) { return new HashRing(virtual_nodes); }
void tpu_ring_destroy(void* h) { delete static_cast<HashRing*>(h); }
void tpu_ring_add(void* h, const char* node) {
  static_cast<HashRing*>(h)->AddNode(node);
}
void tpu_ring_remove(void* h, const char* node) {
  static_cast<HashRing*>(h)->RemoveNode(node);
}

// Returns 1 and allocates *node_out on success, 0 if the ring is empty.
int tpu_ring_get(void* h, const char* key, char** node_out,
                 std::size_t* nlen_out) {
  std::string node;
  if (!static_cast<HashRing*>(h)->GetNode(key, &node)) return 0;
  *node_out = AllocCopy(node);
  *nlen_out = node.size();
  return 1;
}

// Distinct nodes in ring order, framed as repeated
// <uint32 little-endian length><bytes> records so arbitrary node names
// (including '\n') round-trip exactly. Caller frees with tpu_free.
int tpu_ring_all_nodes(void* h, char** out, std::size_t* len_out) {
  std::string joined;
  for (const auto& n : static_cast<HashRing*>(h)->AllNodes()) {
    std::uint32_t len = static_cast<std::uint32_t>(n.size());
    joined.append(reinterpret_cast<const char*>(&len), sizeof(len));
    joined += n;
  }
  *out = AllocCopy(joined);
  *len_out = joined.size();
  return 1;
}

std::size_t tpu_ring_num_nodes(void* h) {
  return static_cast<HashRing*>(h)->NumNodes();
}

std::uint32_t tpu_fnv1a(const char* key, std::size_t klen) {
  return HashRing::Fnv1a(std::string(key, klen));
}

// ----- circuit breaker ------------------------------------------------------

void* tpu_breaker_create(int failure_threshold, int success_threshold,
                         double timeout_s) {
  return new Breaker(failure_threshold, success_threshold, timeout_s);
}
void tpu_breaker_destroy(void* h) { delete static_cast<Breaker*>(h); }
int tpu_breaker_allow(void* h) {
  return static_cast<Breaker*>(h)->AllowRequest() ? 1 : 0;
}
void tpu_breaker_success(void* h) { static_cast<Breaker*>(h)->RecordSuccess(); }
void tpu_breaker_failure(void* h) { static_cast<Breaker*>(h)->RecordFailure(); }
int tpu_breaker_state(void* h) { return static_cast<Breaker*>(h)->state(); }
int tpu_breaker_failures(void* h) {
  return static_cast<Breaker*>(h)->failure_count();
}
int tpu_breaker_successes(void* h) {
  return static_cast<Breaker*>(h)->success_count();
}

// ----- batch queue ----------------------------------------------------------

void* tpu_bq_create(std::size_t max_batch, double timeout_s) {
  return new BatchQueue(max_batch, timeout_s);
}
void tpu_bq_destroy(void* h) { delete static_cast<BatchQueue*>(h); }

long long tpu_bq_push(void* h, const char* data, std::size_t len) {
  return static_cast<BatchQueue*>(h)->Push(std::string(data, len));
}

// Pops up to min(max, queue max_batch) items. Fills parallel arrays of
// malloc'd payload pointers (caller frees each with tpu_free), lengths and
// tickets. Returns the item count (0 = timeout with empty queue), or -1
// when closed+drained.
int tpu_bq_pop_batch(void* h, char** bufs, std::size_t* lens,
                     long long* tickets, int max, int* timed_out) {
  std::vector<BatchQueue::Item> items;
  bool to = false;
  if (max <= 0) {
    *timed_out = 0;
    return 0;
  }
  if (!static_cast<BatchQueue*>(h)->PopBatch(
          &items, &to, static_cast<std::size_t>(max))) {
    *timed_out = to ? 1 : 0;
    return -1;
  }
  *timed_out = to ? 1 : 0;
  int n = 0;
  for (auto& item : items) {
    bufs[n] = AllocCopy(item.payload);
    lens[n] = item.payload.size();
    tickets[n] = item.ticket;
    ++n;
  }
  return n;
}

void tpu_bq_close(void* h) { static_cast<BatchQueue*>(h)->Close(); }
std::size_t tpu_bq_size(void* h) { return static_cast<BatchQueue*>(h)->Size(); }

// ----- native HTTP front ----------------------------------------------------

void* tpu_front_create(int port, int virtual_nodes, int fake_cached_us) {
  return new HttpFront(port, virtual_nodes, fake_cached_us);
}
void tpu_front_destroy(void* h) { delete static_cast<HttpFront*>(h); }

// lru_handle must be a tpu_lru_create handle; breaker_handle a
// tpu_breaker_create handle or NULL. The front borrows both (the Python
// WorkerNode/Gateway keep ownership and share the same objects).
void tpu_front_add_lane(void* h, const char* name, void* lru_handle,
                        void* breaker_handle) {
  static_cast<HttpFront*>(h)->AddLane(name,
                                      static_cast<LruCache*>(lru_handle),
                                      static_cast<Breaker*>(breaker_handle));
}
void tpu_front_set_lane_enabled(void* h, const char* name, int enabled) {
  static_cast<HttpFront*>(h)->SetLaneEnabled(name, enabled != 0);
}
void tpu_front_set_handler(void* h, tpucore::PyHandler handler) {
  static_cast<HttpFront*>(h)->SetHandler(handler);
}
int tpu_front_start(void* h) { return static_cast<HttpFront*>(h)->Start(); }
void tpu_front_stop(void* h) { static_cast<HttpFront*>(h)->Stop(); }
std::uint64_t tpu_front_lane_total(void* h, const char* name) {
  return static_cast<HttpFront*>(h)->LaneTotal(name);
}
std::uint64_t tpu_front_lane_hits(void* h, const char* name) {
  return static_cast<HttpFront*>(h)->LaneHits(name);
}

// Called by the Python fallback handler (inside the handler callback) to
// deliver its response; the front copies the bytes before returning.
void tpu_front_reply(void* reply_ctx, int status, const char* data,
                     std::size_t len) {
  auto* slot = static_cast<ReplySlot*>(reply_ctx);
  slot->status = status;
  slot->body.assign(data, len);
}

// Variant carrying an explicit Content-Type (e.g. /metrics' Prometheus
// text exposition). Kept separate so older .so builds stay ABI-compatible
// with the plain tpu_front_reply.
void tpu_front_reply2(void* reply_ctx, int status, const char* data,
                      std::size_t len, const char* content_type) {
  auto* slot = static_cast<ReplySlot*>(reply_ctx);
  slot->status = status;
  slot->body.assign(data, len);
  if (content_type != nullptr && content_type[0] != '\0') {
    slot->content_type = content_type;
  }
}

}  // extern "C"
