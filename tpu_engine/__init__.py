"""tpu_engine — a TPU-native distributed inference serving framework.

Built from scratch with the capabilities of the reference system
`AbhiramDodda/distributed-inference-engine-cpp` (a C++17 gateway/worker ONNX
serving stack), re-designed TPU-first:

- compute path: JAX/XLA with shape-bucketed compiled-executable caches,
  bfloat16 on the MXU, and Pallas kernels for hot ops;
- scale-out: ``jax.sharding.Mesh`` + ``pjit``/``shard_map`` over ICI/DCN
  instead of HTTP fan-out to replica processes;
- runtime core (LRU result cache, consistent-hash ring, circuit breaker,
  batch queue): native C++ (``tpu_engine/native``) with ctypes bindings and
  pure-Python fallbacks;
- external API: wire-compatible with the reference's ``POST /infer``,
  ``GET /health``, ``GET /stats`` JSON schemas so its ``benchmark.py`` and
  ``diagnostics.sh`` run unmodified.

See ``SURVEY.md`` at the repo root for the reference's structural analysis
and the parity inventory this package implements.
"""

__version__ = "0.1.0"
