"""Per-lane circuit breaker (CLOSED → OPEN → HALF_OPEN state machine).

Capability parity with the reference breaker
(``/root/reference/src/circuit_breaker.cpp:1-69`` /
``include/circuit_breaker.h:1-44``), semantics preserved exactly because the
fault-injection benchmark scenario depends on them:

- ``failure_threshold`` counts *consecutive* failures — any success while
  CLOSED resets the count (reference ``circuit_breaker.cpp:26-37``);
- OPEN transitions to HALF_OPEN after ``timeout`` elapses since the last
  failure, letting one probe stream through (``:12-24``);
- any failure while HALF_OPEN reopens immediately (``:39-47``);
- ``success_threshold`` consecutive HALF_OPEN successes close the circuit.

In the TPU-native gateway these guard per-chip dispatch lanes: the failure
signals are XLA/PJRT errors and dispatch timeouts rather than HTTP errors
(SURVEY.md §5 "failure detection").
"""

from __future__ import annotations

import enum
import threading
import time


class CircuitState(enum.Enum):
    CLOSED = "CLOSED"
    OPEN = "OPEN"
    HALF_OPEN = "HALF_OPEN"


class CircuitBreaker:
    """Thread-safe breaker; defaults mirror the reference gateway config
    (5 failures / 2 successes / 30 s, ``gateway.cpp:19-23``)."""

    def __init__(
        self,
        failure_threshold: int = 5,
        success_threshold: int = 2,
        timeout_seconds: float = 30.0,
        clock=time.monotonic,
    ):
        self._failure_threshold = int(failure_threshold)
        self._success_threshold = int(success_threshold)
        self._timeout = float(timeout_seconds)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CircuitState.CLOSED
        self._failure_count = 0
        self._success_count = 0
        self._last_failure_time = clock()

    def allow_request(self) -> bool:
        with self._lock:
            if self._state is CircuitState.OPEN:
                if self._clock() - self._last_failure_time >= self._timeout:
                    self._state = CircuitState.HALF_OPEN
                    self._success_count = 0
                    return True
                return False
            return True

    def record_success(self) -> None:
        with self._lock:
            if self._state is CircuitState.HALF_OPEN:
                self._success_count += 1
                if self._success_count >= self._success_threshold:
                    self._state = CircuitState.CLOSED
                    self._failure_count = 0
            else:
                self._failure_count = 0

    def record_failure(self) -> None:
        with self._lock:
            self._failure_count += 1
            self._last_failure_time = self._clock()
            if (
                self._failure_count >= self._failure_threshold
                or self._state is CircuitState.HALF_OPEN
            ):
                self._state = CircuitState.OPEN

    # The observability properties below read without the lock on
    # purpose: each is a single reference/int read (atomic under the
    # GIL), staleness is acceptable for /stats, and taking the lock here
    # would let a stats scrape contend with the dispatch path.

    @property
    def state(self) -> CircuitState:
        return self._state  # lint: lockfree-ok atomic enum-ref read for /stats

    @property
    def failure_count(self) -> int:
        return self._failure_count  # lint: lockfree-ok atomic int read for /stats

    @property
    def success_count(self) -> int:
        return self._success_count  # lint: lockfree-ok atomic int read for /stats

    def state_name(self) -> str:
        """String form used by ``GET /stats`` (reference ``gateway.cpp:67-74``)."""
        return self._state.value  # lint: lockfree-ok atomic enum-ref read for /stats
