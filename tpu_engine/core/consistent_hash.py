"""Consistent-hash routing ring (FNV-1a, virtual nodes).

Capability parity with the reference ring
(``/root/reference/src/consistent_hash.cpp:1-70`` /
``include/consistent_hash.h:1-25``): 32-bit FNV-1a over ``"{node}#{i}"``
virtual-node labels (150 vnodes per physical node by default), clockwise
``lower_bound`` lookup with wraparound, ring-order node enumeration, and a
distribution probe for testing.

In the TPU-native deployment the "nodes" are dispatch lanes — one per TPU
chip or per replica group on a ``jax.sharding.Mesh`` — rather than remote
HTTP workers; see ``tpu_engine.serving.gateway``.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Sequence

_FNV_OFFSET = 2166136261
_FNV_PRIME = 16777619
_MASK32 = 0xFFFFFFFF


def fnv1a_32(key: str) -> int:
    """32-bit FNV-1a, identical constants to reference ``consistent_hash.cpp:6-14``."""
    h = _FNV_OFFSET
    for b in key.encode("utf-8"):
        h ^= b
        h = (h * _FNV_PRIME) & _MASK32
    return h


class ConsistentHash:
    """Hash ring mapping request keys to node names.

    Ring storage is a sorted list of vnode hashes plus a hash→node dict;
    hash collisions overwrite, matching the reference's ``std::map`` insert
    (``consistent_hash.cpp:16-23``).
    """

    DEFAULT_VIRTUAL_NODES = 150  # reference include/consistent_hash.h:12

    def __init__(self, virtual_nodes: int = DEFAULT_VIRTUAL_NODES):
        self._virtual_nodes = int(virtual_nodes)
        self._ring: Dict[int, str] = {}
        self._sorted_hashes: List[int] = []
        # Per-node vnode WEIGHT (absent = 1, the reference behavior):
        # a weight-w node registers w * virtual_nodes vnodes — the
        # topology-aware gateway maps virtual nodes onto CHIPS, so a
        # TP=4 lane (one model spanning 4 chips, 4x the KV pool) owns
        # 4x the hash share of a single-chip lane.
        self._weights: Dict[str, int] = {}
        self._lock = threading.Lock()

    @property
    def virtual_nodes(self) -> int:
        return self._virtual_nodes

    def add_node(self, node: str, weight: int = 1) -> None:
        """Insert ``weight * virtual_nodes`` vnodes labelled ``node#i``
        (reference ``:16-23``; weight 1 = the reference-exact ring).
        Re-adding with a different weight RESIZES the node's vnode set
        in place (the topology prober re-weights lanes as /health
        labels arrive)."""
        weight = max(1, int(weight))
        with self._lock:
            self._resize_locked(node, self._weights.get(node, 0), weight)

    def reweight_node(self, node: str, weight: int) -> bool:
        """Resize an EXISTING node's vnode set to ``weight`` — the
        membership check and the resize happen under ONE lock
        acquisition, so a concurrent ``remove_node`` can never
        interleave between them (the add+weight churn race the
        topology prober previously had to detect and undo by hand:
        check-then-``add_node`` could resurrect a just-removed lane's
        vnodes). Returns False — ring untouched — when the node is not
        a member."""
        weight = max(1, int(weight))
        with self._lock:
            prev = self._weights.get(node)
            if prev is None:
                return False
            self._resize_locked(node, prev, weight)
            return True

    def _resize_locked(self, node: str, prev: int, weight: int) -> None:
        """Grow or shrink ``node``'s vnode set from ``prev`` to
        ``weight`` labels x virtual_nodes (caller holds the lock)."""
        if weight < prev:
            self._drop_labels(node, range(weight * self._virtual_nodes,
                                          prev * self._virtual_nodes))
        for i in range(prev * self._virtual_nodes,
                       weight * self._virtual_nodes):
            h = fnv1a_32(f"{node}#{i}")
            if h not in self._ring:
                bisect.insort(self._sorted_hashes, h)
            self._ring[h] = node
        self._weights[node] = weight

    def _drop_labels(self, node: str, label_range) -> None:
        """Erase this node's vnodes for label indices in ``label_range``
        (caller holds the lock)."""
        for i in label_range:
            h = fnv1a_32(f"{node}#{i}")
            if self._ring.get(h) == node:
                del self._ring[h]
                idx = bisect.bisect_left(self._sorted_hashes, h)
                if idx < len(self._sorted_hashes) \
                        and self._sorted_hashes[idx] == h:
                    self._sorted_hashes.pop(idx)

    def node_weight(self, node: str) -> int:
        with self._lock:
            return self._weights.get(node, 0)

    def remove_node(self, node: str) -> None:
        """Erase the node's vnodes (reference ``:25-32``) — enables elastic scale-down,
        which the reference declared but never wired up (SURVEY.md §5)."""
        with self._lock:
            weight = self._weights.pop(node, 1)
            self._drop_labels(node, range(weight * self._virtual_nodes))

    def get_node(self, key: str) -> str:
        """First vnode clockwise of ``hash(key)``, wrapping to ring start
        (reference ``:34-45``)."""
        with self._lock:
            if not self._sorted_hashes:
                raise RuntimeError("hash ring is empty")
            h = fnv1a_32(key)
            idx = bisect.bisect_left(self._sorted_hashes, h)
            if idx == len(self._sorted_hashes):
                idx = 0
            return self._ring[self._sorted_hashes[idx]]

    def get_all_nodes(self) -> List[str]:
        """Distinct nodes in ring order, first-occurrence dedup (reference ``:47-59``).

        Ring order is the failover order used by the gateway
        (``gateway.cpp:51-59``).
        """
        with self._lock:
            seen = set()
            out: List[str] = []
            for h in self._sorted_hashes:
                n = self._ring[h]
                if n not in seen:
                    seen.add(n)
                    out.append(n)
            return out

    def size(self) -> int:
        """Number of distinct physical nodes."""
        with self._lock:
            return len(set(self._ring.values()))

    def get_distribution(self, keys: Sequence[str]) -> Dict[str, int]:
        """Per-node assignment counts over ``keys`` — the test/debug probe the
        reference shipped but never called (``consistent_hash.cpp:61-70``)."""
        return compute_distribution(self, keys)


def compute_distribution(ring, keys: Sequence[str]) -> Dict[str, int]:
    """Shared by the Python and native rings (derived logic, not ring state)."""
    counts: Dict[str, int] = {}
    for k in keys:
        n = ring.get_node(k)
        counts[n] = counts.get(n, 0) + 1
    return counts
