"""ctypes bindings for the native C++ runtime core (libtpucore.so).

Exposes ``NativeLRUCache``, ``NativeConsistentHash``, ``NativeCircuitBreaker``
and ``NativeBatchQueue`` with the same Python API as the pure-Python
implementations in ``tpu_engine.core`` so the two are interchangeable (and
are tested against the same suite, see ``tests/impl_params.py``).

The shared library is built from ``tpu_engine/native`` (CMake or
``build.sh``). If it is absent, ``available()`` triggers a one-shot quiet
build attempt with g++; failing that, callers fall back to pure Python.
"""

from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
import threading
from typing import Any, List, Optional

from tpu_engine.core.circuit_breaker import CircuitState

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_CANDIDATES = [
    os.path.join(_NATIVE_DIR, "libtpucore.so"),
    os.path.join(os.path.dirname(_NATIVE_DIR), "..", "build", "native", "libtpucore.so"),
]

_lib = None
_load_lock = threading.Lock()
_load_attempted = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_size = ctypes.c_size_t
    P = ctypes.c_void_p
    lib.tpu_free.argtypes = [ctypes.c_void_p]
    lib.tpu_lru_create.restype = P
    lib.tpu_lru_create.argtypes = [c_size]
    lib.tpu_lru_destroy.argtypes = [P]
    lib.tpu_lru_get.restype = ctypes.c_int
    lib.tpu_lru_get.argtypes = [P, ctypes.c_char_p, c_size,
                                ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(c_size)]
    lib.tpu_lru_put.argtypes = [P, ctypes.c_char_p, c_size, ctypes.c_char_p, c_size]
    lib.tpu_lru_clear.argtypes = [P]
    lib.tpu_lru_size.restype = c_size
    lib.tpu_lru_size.argtypes = [P]
    lib.tpu_lru_capacity.restype = c_size
    lib.tpu_lru_capacity.argtypes = [P]
    lib.tpu_lru_hits.restype = ctypes.c_uint64
    lib.tpu_lru_hits.argtypes = [P]
    lib.tpu_lru_misses.restype = ctypes.c_uint64
    lib.tpu_lru_misses.argtypes = [P]

    lib.tpu_ring_create.restype = P
    lib.tpu_ring_create.argtypes = [ctypes.c_int]
    lib.tpu_ring_destroy.argtypes = [P]
    lib.tpu_ring_add.argtypes = [P, ctypes.c_char_p]
    lib.tpu_ring_remove.argtypes = [P, ctypes.c_char_p]
    lib.tpu_ring_get.restype = ctypes.c_int
    lib.tpu_ring_get.argtypes = [P, ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(c_size)]
    lib.tpu_ring_all_nodes.restype = ctypes.c_int
    lib.tpu_ring_all_nodes.argtypes = [P, ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(c_size)]
    lib.tpu_ring_num_nodes.restype = c_size
    lib.tpu_ring_num_nodes.argtypes = [P]
    lib.tpu_fnv1a.restype = ctypes.c_uint32
    lib.tpu_fnv1a.argtypes = [ctypes.c_char_p, c_size]

    lib.tpu_breaker_create.restype = P
    lib.tpu_breaker_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_double]
    lib.tpu_breaker_destroy.argtypes = [P]
    for fn in ("tpu_breaker_allow", "tpu_breaker_state",
               "tpu_breaker_failures", "tpu_breaker_successes"):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = [P]
    lib.tpu_breaker_success.argtypes = [P]
    lib.tpu_breaker_failure.argtypes = [P]

    lib.tpu_bq_create.restype = P
    lib.tpu_bq_create.argtypes = [c_size, ctypes.c_double]
    lib.tpu_bq_destroy.argtypes = [P]
    lib.tpu_bq_push.restype = ctypes.c_longlong
    lib.tpu_bq_push.argtypes = [P, ctypes.c_char_p, c_size]
    lib.tpu_bq_pop_batch.restype = ctypes.c_int
    lib.tpu_bq_pop_batch.argtypes = [P, ctypes.POINTER(ctypes.c_void_p),
                                     ctypes.POINTER(c_size), ctypes.POINTER(ctypes.c_longlong),
                                     ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.tpu_bq_close.argtypes = [P]
    lib.tpu_bq_size.restype = c_size
    lib.tpu_bq_size.argtypes = [P]

    lib.tpu_front_create.restype = P
    lib.tpu_front_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.tpu_front_destroy.argtypes = [P]
    lib.tpu_front_add_lane.argtypes = [P, ctypes.c_char_p, P, P]
    lib.tpu_front_set_lane_enabled.argtypes = [P, ctypes.c_char_p, ctypes.c_int]
    lib.tpu_front_set_handler.argtypes = [P, HANDLER_FN]
    lib.tpu_front_start.restype = ctypes.c_int
    lib.tpu_front_start.argtypes = [P]
    lib.tpu_front_stop.argtypes = [P]
    lib.tpu_front_lane_total.restype = ctypes.c_uint64
    lib.tpu_front_lane_total.argtypes = [P, ctypes.c_char_p]
    lib.tpu_front_lane_hits.restype = ctypes.c_uint64
    lib.tpu_front_lane_hits.argtypes = [P, ctypes.c_char_p]
    lib.tpu_front_reply.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_char_p, c_size]
    if hasattr(lib, "tpu_front_reply2"):  # older .so: plain reply only
        lib.tpu_front_reply2.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                         ctypes.c_char_p, c_size,
                                         ctypes.c_char_p]
    if hasattr(lib, "tpu_json_encode_f32"):  # older .so: python fallback
        lib.tpu_json_encode_f32.restype = c_size
        lib.tpu_json_encode_f32.argtypes = [
            ctypes.c_void_p, c_size, ctypes.POINTER(ctypes.c_void_p)]
    return lib


# void handler(reply_ctx, method, path, body, body_len)
HANDLER_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_char_p, ctypes.c_char_p,
                              ctypes.c_size_t)


def _try_load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _load_lock:
        if _lib is not None:
            return _lib
        if _load_attempted:
            return None
        _load_attempted = True
        path = next((p for p in _LIB_CANDIDATES if os.path.exists(p)), None)
        if path is None and os.environ.get("TPU_ENGINE_NO_NATIVE_BUILD") != "1":
            # Build to a pid-suffixed temp name, then atomically rename: two
            # processes cold-starting together must not interleave g++ output
            # into the same file (a corrupt .so would poison all future runs).
            tmp_name = f"libtpucore.so.tmp.{os.getpid()}"
            try:
                subprocess.run(
                    ["bash", os.path.join(_NATIVE_DIR, "build.sh"), tmp_name],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(os.path.join(_NATIVE_DIR, tmp_name), _LIB_CANDIDATES[0])
                path = _LIB_CANDIDATES[0]
            except Exception:
                try:
                    os.unlink(os.path.join(_NATIVE_DIR, tmp_name))
                except OSError:
                    pass
                return None
        if path is None or not os.path.exists(path):
            return None
        try:
            _lib = _configure(ctypes.CDLL(path))
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _try_load() is not None


def _take_bytes(lib, ptr: ctypes.c_void_p, length: int) -> bytes:
    try:
        return ctypes.string_at(ptr, length)
    finally:
        lib.tpu_free(ptr)


def json_encode_f32(arr) -> Optional[bytes]:
    """``[a,b,...]`` JSON fragment for a float array via the C encoder
    (%.6g, ~10x faster than json.dumps and GIL-free for the duration).
    None when the native core (or the symbol, in an older .so) is absent —
    callers fall back to a Python encode."""
    lib = _try_load()
    if lib is None or not hasattr(lib, "tpu_json_encode_f32"):
        return None
    import numpy as np

    a = np.ascontiguousarray(arr, dtype=np.float32)
    out = ctypes.c_void_p()
    length = lib.tpu_json_encode_f32(
        a.ctypes.data_as(ctypes.c_void_p), a.size, ctypes.byref(out))
    if not out:
        return None  # allocation failure: let the Python path serve
    return _take_bytes(lib, out, length)


class NativeLRUCache:
    """Byte-blob LRU; arbitrary Python values round-trip via pickle.

    Keys must be ``bytes`` — the serving path keys by the serialized input
    tensor. (The pure-Python LRUCache accepts any hashable; restricting the
    native contract to bytes avoids pickle-canonicalization mismatches like
    ``1`` vs ``1.0``, which hash-equal as dict keys but differ as pickles.)
    """

    def __init__(self, capacity: int, raw: bool = False):
        """``raw=True`` stores values as verbatim bytes (no pickle) — the
        contract that lets the native HTTP front read entries directly."""
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._lib = _try_load()
        if self._lib is None:
            raise RuntimeError("libtpucore.so is not available")
        self._raw = raw
        self._h = self._lib.tpu_lru_create(capacity)

    @property
    def handle(self):
        """The underlying C handle (for tpu_front_add_lane)."""
        return self._h

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.tpu_lru_destroy(h)
            self._h = None

    @staticmethod
    def _key_bytes(key) -> bytes:
        if not isinstance(key, bytes):
            raise TypeError(f"NativeLRUCache keys must be bytes, got {type(key).__name__}")
        return key

    def get(self, key) -> Optional[Any]:
        out = ctypes.c_void_p()
        n = ctypes.c_size_t()
        k = self._key_bytes(key)
        if not self._lib.tpu_lru_get(self._h, k, len(k), ctypes.byref(out), ctypes.byref(n)):
            return None
        blob = _take_bytes(self._lib, out, n.value)
        return blob if self._raw else pickle.loads(blob)

    def put(self, key, value: Any) -> None:
        k = self._key_bytes(key)
        v = value if self._raw else pickle.dumps(value)
        if not isinstance(v, bytes):
            raise TypeError("raw NativeLRUCache values must be bytes")
        self._lib.tpu_lru_put(self._h, k, len(k), v, len(v))

    def clear(self) -> None:
        self._lib.tpu_lru_clear(self._h)

    def size(self) -> int:
        return self._lib.tpu_lru_size(self._h)

    @property
    def capacity(self) -> int:
        return self._lib.tpu_lru_capacity(self._h)

    @property
    def hits(self) -> int:
        return self._lib.tpu_lru_hits(self._h)

    @property
    def misses(self) -> int:
        return self._lib.tpu_lru_misses(self._h)

    def hit_rate(self) -> float:
        from tpu_engine.core.lru_cache import compute_hit_rate

        return compute_hit_rate(self.hits, self.misses)


class NativeConsistentHash:
    def __init__(self, virtual_nodes: int = 150):
        self._lib = _try_load()
        if self._lib is None:
            raise RuntimeError("libtpucore.so is not available")
        self._h = self._lib.tpu_ring_create(virtual_nodes)
        self._virtual_nodes = virtual_nodes

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.tpu_ring_destroy(h)
            self._h = None

    @property
    def virtual_nodes(self) -> int:
        return self._virtual_nodes

    def add_node(self, node: str) -> None:
        self._lib.tpu_ring_add(self._h, node.encode())

    def remove_node(self, node: str) -> None:
        self._lib.tpu_ring_remove(self._h, node.encode())

    def get_node(self, key: str) -> str:
        out = ctypes.c_void_p()
        n = ctypes.c_size_t()
        if not self._lib.tpu_ring_get(self._h, key.encode(), ctypes.byref(out), ctypes.byref(n)):
            raise RuntimeError("hash ring is empty")
        return _take_bytes(self._lib, out, n.value).decode()

    def get_all_nodes(self) -> List[str]:
        out = ctypes.c_void_p()
        n = ctypes.c_size_t()
        self._lib.tpu_ring_all_nodes(self._h, ctypes.byref(out), ctypes.byref(n))
        buf = _take_bytes(self._lib, out, n.value)
        # Repeated <uint32 LE length><bytes> records (see tpu_ring_all_nodes).
        nodes, pos = [], 0
        while pos < len(buf):
            ln = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
            nodes.append(buf[pos:pos + ln].decode())
            pos += ln
        return nodes

    def size(self) -> int:
        return self._lib.tpu_ring_num_nodes(self._h)

    def get_distribution(self, keys) -> dict:
        from tpu_engine.core.consistent_hash import compute_distribution

        return compute_distribution(self, keys)


class NativeCircuitBreaker:
    _STATES = {0: CircuitState.CLOSED, 1: CircuitState.OPEN, 2: CircuitState.HALF_OPEN}

    def __init__(self, failure_threshold: int = 5, success_threshold: int = 2,
                 timeout_seconds: float = 30.0):
        self._lib = _try_load()
        if self._lib is None:
            raise RuntimeError("libtpucore.so is not available")
        self._h = self._lib.tpu_breaker_create(failure_threshold, success_threshold,
                                               float(timeout_seconds))

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.tpu_breaker_destroy(h)
            self._h = None

    def allow_request(self) -> bool:
        return bool(self._lib.tpu_breaker_allow(self._h))

    def record_success(self) -> None:
        self._lib.tpu_breaker_success(self._h)

    def record_failure(self) -> None:
        self._lib.tpu_breaker_failure(self._h)

    @property
    def state(self) -> CircuitState:
        return self._STATES[self._lib.tpu_breaker_state(self._h)]

    @property
    def failure_count(self) -> int:
        return self._lib.tpu_breaker_failures(self._h)

    @property
    def success_count(self) -> int:
        return self._lib.tpu_breaker_successes(self._h)

    def state_name(self) -> str:
        return self.state.value


class NativeBatchQueue:
    """Native MPMC batch queue; the timed PopBatch wait releases the GIL."""

    def __init__(self, max_batch: int, timeout_s: float):
        if max_batch <= 0:
            raise ValueError("max_batch must be positive")
        self._lib = _try_load()
        if self._lib is None:
            raise RuntimeError("libtpucore.so is not available")
        self._max = int(max_batch)
        self._h = self._lib.tpu_bq_create(self._max, float(timeout_s))

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.tpu_bq_destroy(h)
            self._h = None

    def push(self, payload: bytes) -> int:
        """Returns the ticket id, or -1 if the queue is closed."""
        return self._lib.tpu_bq_push(self._h, payload, len(payload))

    def pop_batch(self):
        """Returns (items, timed_out) where items is a list of
        (ticket, payload) — or (None, timed_out) when closed and drained."""
        bufs = (ctypes.c_void_p * self._max)()
        lens = (ctypes.c_size_t * self._max)()
        tickets = (ctypes.c_longlong * self._max)()
        timed_out = ctypes.c_int()
        n = self._lib.tpu_bq_pop_batch(self._h, bufs, lens, tickets, self._max,
                                       ctypes.byref(timed_out))
        if n < 0:
            return None, bool(timed_out.value)
        items = [
            (tickets[i], _take_bytes(self._lib, ctypes.c_void_p(bufs[i]), lens[i]))
            for i in range(n)
        ]
        return items, bool(timed_out.value)

    def close(self) -> None:
        self._lib.tpu_bq_close(self._h)

    def size(self) -> int:
        return self._lib.tpu_bq_size(self._h)


def native_fnv1a_32(key: str) -> int:
    lib = _try_load()
    if lib is None:
        raise RuntimeError("libtpucore.so is not available")
    b = key.encode()
    return lib.tpu_fnv1a(b, len(b))


class NativeHttpFront:
    """The C++ HTTP front door (tpu_engine/native/http_front.h).

    Serves /infer cache hits entirely in C++ (ring lookup + raw-mode LRU
    fetch + response splice, no GIL); everything else — cache misses,
    /generate, health/stats/admin — calls the Python ``fallback`` handler:
    ``fallback(method: str, path: str, body: bytes) -> (status, bytes)``.
    """

    def __init__(self, port: int, fallback, virtual_nodes: int = 150,
                 fake_cached_latency_us: int = 50):
        self._lib = _try_load()
        if self._lib is None:
            raise RuntimeError("libtpucore.so is not available")
        self._h = self._lib.tpu_front_create(port, virtual_nodes,
                                             fake_cached_latency_us)
        self.port = port
        self._lanes: List[str] = []
        lib = self._lib

        can_ctype = hasattr(lib, "tpu_front_reply2")

        def _handler(reply_ctx, method, path, body, body_len):
            ctype = None
            try:
                result = fallback(method.decode(), path.decode(), body or b"")
                # (status, payload) or (status, payload, content_type) —
                # the latter e.g. /metrics' text/plain exposition.
                status, payload = result[0], result[1]
                if len(result) == 3:
                    ctype = result[2]
            except Exception as exc:  # never let an exception cross ctypes
                status, payload = 500, (
                    b'{"error": ' + _json_str(str(exc)) + b"}")
            if ctype is not None and can_ctype:
                lib.tpu_front_reply2(reply_ctx, status, payload,
                                     len(payload), ctype.encode())
            else:
                lib.tpu_front_reply(reply_ctx, status, payload, len(payload))

        # Keep a reference: the C side stores the raw function pointer.
        self._handler_ref = HANDLER_FN(_handler)
        self._lib.tpu_front_set_handler(self._h, self._handler_ref)

    def add_lane(self, name: str, cache: "NativeLRUCache",
                 breaker: "Optional[NativeCircuitBreaker]" = None) -> None:
        if not getattr(cache, "_raw", False):
            raise ValueError("front lanes need raw-mode NativeLRUCache")
        self._lanes.append(name)
        self._lib.tpu_front_add_lane(
            self._h, name.encode(), cache.handle,
            breaker._h if breaker is not None else None)

    def set_lane_enabled(self, name: str, enabled: bool) -> None:
        self._lib.tpu_front_set_lane_enabled(self._h, name.encode(),
                                             1 if enabled else 0)

    def start(self) -> int:
        port = self._lib.tpu_front_start(self._h)
        if port < 0:
            raise OSError(f"native front failed to bind port {self.port}")
        self.port = port
        return port

    def stop(self) -> None:
        if self._h:
            self._lib.tpu_front_stop(self._h)

    def lane_counters(self, name: str):
        n = name.encode()
        return (int(self._lib.tpu_front_lane_total(self._h, n)),
                int(self._lib.tpu_front_lane_hits(self._h, n)))

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.tpu_front_stop(h)
            lib.tpu_front_destroy(h)
            self._h = None


def _json_str(s: str) -> bytes:
    import json

    return json.dumps(s).encode()
