"""Pure-logic runtime cores: LRU cache, consistent-hash ring, circuit breaker.

These are the pure-Python reference implementations. Native C++ equivalents
with identical semantics live in ``tpu_engine/native`` and are exposed via
``tpu_engine.core.native`` (ctypes) when the shared library has been built;
``tests/impl_params.py`` runs the same test suite against both.
"""

from tpu_engine.core.lru_cache import LRUCache
from tpu_engine.core.consistent_hash import ConsistentHash
from tpu_engine.core.circuit_breaker import CircuitBreaker, CircuitState

__all__ = ["LRUCache", "ConsistentHash", "CircuitBreaker", "CircuitState"]
