"""Thread-safe LRU result cache.

Capability parity with the reference's header-only template
(``/root/reference/include/lru_cache.h:1-97``): ``get``/``put``/``clear``,
capacity-bounded eviction from the LRU end, and hit/miss counters surfaced as
``cache_hit_rate`` in worker health.

Design differences from the reference (deliberate):

- Keys are opaque ``bytes`` (callers key by the exact serialized input
  tensor). The reference hashed ``vector<float>`` with a *sampled* hash
  (first/middle/last element, ``lru_cache.h:84-96``, weakness admitted at
  ``README.md:353``); Python's ``bytes.__hash__`` covers the full key, so
  equal-prefix inputs cannot degenerate into one hash bucket.
- Statistics reads are lock-free snapshots (ints are atomic under the GIL).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Hashable, Optional


class LRUCache:
    """Mutex-guarded LRU map with hit/miss accounting.

    Mirrors ``LRUCache<Key,Value>`` semantics: ``get`` promotes to MRU
    (reference ``lru_cache.h:18-28``), ``put`` updates-and-promotes or
    inserts-and-evicts (``:29-48``).
    """

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._capacity = int(capacity)
        self._map: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0

    def get(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            try:
                value = self._map[key]
            except KeyError:
                self._misses += 1
                return None
            self._map.move_to_end(key)
            self._hits += 1
            return value

    def put(self, key: Hashable, value: Any) -> None:
        with self._lock:
            if key in self._map:
                self._map[key] = value
                self._map.move_to_end(key)
                return
            if len(self._map) >= self._capacity:
                self._map.popitem(last=False)
            self._map[key] = value

    def clear(self) -> None:
        with self._lock:
            self._map.clear()
            self._hits = 0
            self._misses = 0

    def size(self) -> int:
        return len(self._map)

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def hits(self) -> int:
        return self._hits

    @property
    def misses(self) -> int:
        return self._misses

    def hit_rate(self) -> float:
        """Fraction of ``get`` calls served from cache (0.0 when untouched).

        Matches ``LRUCache::getHitRate`` (reference ``lru_cache.h:66-71``).
        """
        return compute_hit_rate(self._hits, self._misses)


def compute_hit_rate(hits: int, misses: int) -> float:
    """Shared by the Python and native caches."""
    total = hits + misses
    return (hits / total) if total else 0.0
