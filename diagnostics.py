#!/usr/bin/env python3
"""6-step live-system smoke test — ops parity with the reference's
diagnostics.sh (/root/reference/diagnostics.sh): process check (:9-24),
port check (:27-36), worker /health (:39-56), gateway /stats (:59-68),
direct worker /infer (:71-89), end-to-end gateway /infer (:92-109) — each
with a ✓/✗ verdict and a non-zero exit code when any step fails.

Usage:
  python3 diagnostics.py [--gateway http://localhost:8000]
                         [--workers localhost:8001 localhost:8002 ...]
In combined single-process mode (`serve`), pass only --gateway: worker
health is proxied at /health and there are no separate worker ports.
"""

from __future__ import annotations

import argparse
import http.client
import json
import socket
import subprocess
import sys
import time

OK, FAIL = "✓", "✗"
_results = []
_TOTAL = 6  # --kernel-parity appends step 7, --mixed-parity step 8,
#             --spec-parity step 9, --quant-parity step 10,
#             --ssd-parity step 11, --tp-parity step 12, --failover
#             step 13, --migrate step 14, --disagg step 15,
#             --overload step 16, --elastic step 17, --stitch step 18,
#             --lint step 19


def step(n: int, title: str, ok: bool, detail: str = "") -> None:
    mark = OK if ok else FAIL
    print(f"[{n}/{_TOTAL}] {title}: {mark} {detail}".rstrip())
    _results.append(ok)


def _get(hostport: str, path: str, timeout=5.0):
    host, port = hostport.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _post(hostport: str, path: str, body: dict, timeout=30.0):
    host, port = hostport.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _strip(url: str, default_port: int = 8000) -> str:
    u = url.split("://", 1)[-1].split("/", 1)[0]
    return u if ":" in u else f"{u}:{default_port}"


def main() -> int:
    global _TOTAL
    ap = argparse.ArgumentParser()
    ap.add_argument("--gateway", default="http://localhost:8000")
    ap.add_argument("--workers", nargs="*", default=[])
    ap.add_argument("--kernel-parity", action="store_true",
                    help="step 7: paged-attention kernel vs XLA reference "
                         "parity on this host's backend (in-process, no "
                         "server; compiles a small kernel — seconds on "
                         "CPU, validates Mosaic on a TPU host)")
    ap.add_argument("--mixed-parity", action="store_true",
                    help="step 8: RAGGED paged-attention kernel (the "
                         "--mixed-step read path) vs the XLA gather "
                         "reference at mixed q_lens {1, 7, 16, 17} — "
                         "decode rows and prefill chunks in one batch")
    ap.add_argument("--spec-parity", action="store_true",
                    help="step 9: ragged kernel at the SPECULATIVE "
                         "verify-window shapes (--spec-k serving): "
                         "undrafted decode rows, k+1 verify windows, "
                         "and block-boundary prefill chunks in one "
                         "batch vs the XLA gather reference")
    ap.add_argument("--quant-parity", action="store_true",
                    help="step 10: QUANTIZED (int8 block pool) paged-"
                         "attention kernels vs their dequantizing XLA "
                         "gather references — the fused-dequant decode "
                         "and ragged read paths behind --kv-quantize "
                         "(the on-chip gate before serving int8 KV)")
    ap.add_argument("--ssd-parity", action="store_true",
                    help="step 11: State Space Duality parity — the "
                         "SSD/Mamba chunked matmul-form prefill scan vs "
                         "the O(1) decode recurrence (ops.ssd, the "
                         "state_slab model family behind e.g. mamba2): "
                         "max|Δ| over outputs AND final state must stay "
                         "bounded, the gate before serving the "
                         "matmul-form prefill on a device")
    ap.add_argument("--tp-parity", action="store_true",
                    help="step 12: tensor-parallel serving parity — a "
                         "tp=2 continuous scheduler (sharded params + "
                         "H_kv-sharded KV pool on this host's mesh) vs "
                         "the single-device arm: greedy streams must "
                         "be byte-identical and every mixed tick one "
                         "dispatch (in-process, no server; the gate "
                         "before serving --tp on a device)")
    ap.add_argument("--failover", action="store_true",
                    help="step 13: one scripted kill/resume against a "
                         "local worker pair (spawned here): kill -9 the "
                         "stream's lane mid-generation and print the "
                         "spliced-vs-control diff — the crash-tolerant "
                         "streaming smoke without the full "
                         "fault_injection --crash chaos run")
    ap.add_argument("--migrate", action="store_true",
                    help="step 14: one scripted migrate-mode drain "
                         "against a local worker pair (spawned here): "
                         "drain the stream's lane mid-generation with "
                         "--migrate-streams semantics and print the "
                         "spliced-vs-control diff plus the migration "
                         "counters — the KV-handoff smoke without the "
                         "full fault_injection --migrate chaos run")
    ap.add_argument("--disagg", action="store_true",
                    help="step 15: one scripted prefill→decode handoff "
                         "against a local 1-prefill + 1-decode worker "
                         "pair (spawned here) behind a --disagg "
                         "gateway: stream routes to the prefill lane, "
                         "ships its KV chain, splices on the decode "
                         "lane — prints the spliced-vs-control diff "
                         "plus the handoff counters, the disagg smoke "
                         "without the full fault_injection --disagg "
                         "chaos run")
    ap.add_argument("--overload", action="store_true",
                    help="step 16: overload-control state of the live "
                         "system — the gateway's /stats overload block "
                         "(in-flight gauge, tier/rate-limit sheds, "
                         "pressure) and every lane's current brownout "
                         "ladder stage from /health")
    ap.add_argument("--elastic", action="store_true",
                    help="step 17: elastic-fleet state of the live "
                         "system — the gateway's /admin/fleet status "
                         "(membership, named degraded states like "
                         "spawn-wedged/drain-wedged, controller "
                         "engagement, last observed fleet pressure) "
                         "and the decision counters")
    ap.add_argument("--stitch", action="store_true",
                    help="step 18: one scripted cross-lane stitched "
                         "trace against a local worker pair (spawned "
                         "here) with --trace-stitch armed: drain-migrate "
                         "a live stream to the other lane, then render "
                         "the merged /admin/trace/<request_id> tree — "
                         "lanes touched, span count, hop markers, and "
                         "the orphan count (must be zero)")
    ap.add_argument("--fleet-prefix", action="store_true",
                    help="step 19: one scripted fleet-prefix fetch "
                         "against a local worker pair (spawned here) "
                         "with --prefix-fetch armed: establish one lane "
                         "as the owner of a shared 48-token prefix, "
                         "then a hinted request on the OTHER lane must "
                         "pull the owner's KV chain over HTTP and "
                         "splice it — blocks spliced, remote prefill "
                         "tokens skipped, hint bookkeeping, and "
                         "byte-identity to an unhinted control")
    ap.add_argument("--unified", action="store_true",
                    help="step 20: one scripted unified-pool mixed tick "
                         "(in-process, no server): a decode stream and "
                         "concurrent /score requests share ONE "
                         "continuous scheduler — renders the mixed-row "
                         "tick live (decode rows beside single-tick "
                         "score rows in the same scheduler) and checks "
                         "the scores answer byte-identical to a solo "
                         "control with ticks == dispatches on the "
                         "stateless counter block")
    ap.add_argument("--lint", action="store_true",
                    help="step 21: engine-lint static-analysis suite "
                         "over tpu_engine/ (in-process, no server): lock "
                         "discipline, hot-path trace leaks, "
                         "counters==spans pairing, flag discipline — "
                         "prints the per-rule finding summary")
    args = ap.parse_args()
    _TOTAL = (6 + int(args.kernel_parity) + int(args.mixed_parity)
              + int(args.spec_parity) + int(args.quant_parity)
              + int(args.ssd_parity) + int(args.tp_parity)
              + int(args.failover) + int(args.migrate)
              + int(args.disagg) + int(args.overload)
              + int(args.elastic) + int(args.stitch)
              + int(args.fleet_prefix) + int(args.unified)
              + int(args.lint))
    gw = _strip(args.gateway)
    # Accept both bare host:port (reference diagnostics.sh style) and full
    # http:// URLs — same normalization as the gateway address.
    workers = [_strip(w, default_port=8080) for w in args.workers]
    combined = not workers

    # 1. process check (reference :9-24)
    try:
        out = subprocess.run(
            ["pgrep", "-af", "serving.cli|worker_node|gateway"],
            capture_output=True, text=True).stdout.strip()
        n_proc = len([ln for ln in out.splitlines() if "pgrep" not in ln])
        step(1, "serving processes", n_proc > 0, f"({n_proc} found)")
    except FileNotFoundError:
        step(1, "serving processes", True, "(pgrep unavailable, skipped)")

    # 2. port check (reference :27-36)
    ports_ok = True
    for hp in [gw] + workers:
        host, port = hp.rsplit(":", 1)
        s = socket.socket()
        s.settimeout(2)
        try:
            s.connect((host, int(port)))
        except OSError:
            ports_ok = False
        finally:
            s.close()
    step(2, "ports listening", ports_ok, f"({gw}{' + ' + str(len(workers)) + ' workers' if workers else ''})")

    # 3. worker /health (reference :39-56)
    ok, details = True, []
    targets = workers or [gw]
    for hp in targets:
        try:
            status, body = _get(hp, "/health")
            healthy = status == 200 and body.get("healthy") is True
            ok = ok and healthy
            details.append(f"{body.get('node_id', hp)}:{'up' if healthy else 'DOWN'}")
        except OSError as exc:
            ok = False
            details.append(f"{hp}:{exc}")
    step(3, "worker health", ok, "(" + ", ".join(details) + ")")

    # 4. gateway /stats (reference :59-68)
    try:
        status, body = _get(gw, "/stats")
        n = body.get("total_workers", 0)
        step(4, "gateway stats", status == 200 and n > 0, f"({n} workers)")
    except OSError as exc:
        step(4, "gateway stats", False, f"({exc})")

    # 5. direct worker inference, bypassing the gateway (reference :71-89)
    payload = {"request_id": "diag_direct", "input_data": [1.0, 2.0, 3.0]}
    if combined:
        step(5, "direct worker infer", True, "(combined mode: no direct port, skipped)")
    else:
        try:
            status, body = _post(workers[0], "/infer", payload)
            step(5, "direct worker infer", status == 200 and "output_data" in body,
                 f"({len(body.get('output_data', []))} outputs from {body.get('node_id')})")
        except OSError as exc:
            step(5, "direct worker infer", False, f"({exc})")

    # 6. end-to-end through the gateway (reference :92-109)
    try:
        status, body = _post(gw, "/infer",
                             {"request_id": "diag_e2e", "input_data": [4.0, 5.0, 6.0]})
        step(6, "gateway end-to-end infer", status == 200 and "output_data" in body,
             f"(node {body.get('node_id')}, {body.get('inference_time_us')} us)")
    except OSError as exc:
        step(6, "gateway end-to-end infer", False, f"({exc})")

    # 7 (--kernel-parity): paged-attention Pallas kernel vs XLA reference
    # — the decode read path behind --kv-block-size serving; run on a TPU
    # host this validates the Mosaic compile, elsewhere the interpreter.
    if args.kernel_parity:
        try:
            import jax.numpy as jnp

            from tpu_engine.ops.paged_attention import parity_check

            diff = max(parity_check(),
                       parity_check(n_heads=8, n_kv_heads=2, d_head=16))
            bf16 = parity_check(dtype=jnp.bfloat16)
            step(7, "paged-attention kernel parity",
                 diff < 2e-5 and bf16 < 2e-2,
                 f"(max|Δ| f32 {diff:.2e}, bf16 {bf16:.2e})")
        except Exception as exc:
            step(7, "paged-attention kernel parity", False, f"({exc})")

    # 8 (--mixed-parity): the ragged kernel behind --mixed-step serving —
    # one batch mixing decode rows (q_len 1) and prefill chunks (q_len up
    # to block_size+1, crossing a block boundary) against the XLA gather
    # reference. On a TPU host this validates the Mosaic compile the
    # tunnel-watchdog campaign needs before re-enabling mixed mode.
    if args.mixed_parity:
        n = 6 + int(args.kernel_parity) + 1
        try:
            import jax.numpy as jnp

            from tpu_engine.ops.paged_attention import ragged_parity_check

            diff = max(ragged_parity_check(q_lens=(1, 7, 16, 17)),
                       ragged_parity_check(q_lens=(1, 3, 8, 9),
                                           n_heads=8, n_kv_heads=2,
                                           d_head=16, block_size=8,
                                           table_len=8))
            bf16 = ragged_parity_check(q_lens=(1, 7, 16, 17),
                                       dtype=jnp.bfloat16)
            step(n, "ragged mixed-step kernel parity",
                 diff < 2e-5 and bf16 < 2e-2,
                 f"(max|Δ| f32 {diff:.2e}, bf16 {bf16:.2e})")
        except Exception as exc:
            step(n, "ragged mixed-step kernel parity", False, f"({exc})")

    # 9 (--spec-parity): the ragged kernel at the verify-window shapes
    # the --spec-k scheduler dispatches — greedy identity depends on the
    # verify window's logits matching the plain path's bit-for-bit, so
    # kernel-vs-reference parity here is the on-chip gate before
    # enabling continuous speculation on a device.
    if args.spec_parity:
        n = 6 + int(args.kernel_parity) + int(args.mixed_parity) + 1
        try:
            import jax.numpy as jnp

            from tpu_engine.ops.paged_attention import (
                spec_verify_parity_check,
            )

            diff = max(spec_verify_parity_check(k=4),
                       spec_verify_parity_check(k=3, n_heads=8,
                                                n_kv_heads=2, d_head=16,
                                                block_size=8,
                                                table_len=8))
            bf16 = spec_verify_parity_check(k=4, dtype=jnp.bfloat16)
            step(n, "speculative verify-window kernel parity",
                 diff < 2e-5 and bf16 < 2e-2,
                 f"(max|Δ| f32 {diff:.2e}, bf16 {bf16:.2e})")
        except Exception as exc:
            step(n, "speculative verify-window kernel parity", False,
                 f"({exc})")

    # 10 (--quant-parity): the QUANTIZED read paths behind --kv-quantize
    # int8 — the fused-dequant Pallas kernels (decode + ragged) against
    # the dequantizing XLA gather references. The one-time-write
    # exactness story holds only if the kernel's in-VMEM dequant matches
    # the reference's gathered dequant, so this is the on-chip gate
    # before enabling int8 KV on a device.
    if args.quant_parity:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + 1)
        try:
            from tpu_engine.ops.paged_attention import (
                quant_parity_check,
                quant_ragged_parity_check,
            )

            decode = max(quant_parity_check(),
                         quant_parity_check(n_heads=8, n_kv_heads=2,
                                            d_head=64, block_size=16,
                                            n_blocks=33, table_len=8))
            ragged = quant_ragged_parity_check(q_lens=(1, 7, 16, 17))
            step(n, "quantized (int8) kernel parity",
                 decode < 2e-4 and ragged < 2e-4,
                 f"(max|Δ| decode {decode:.2e}, ragged {ragged:.2e})")
        except Exception as exc:
            step(n, "quantized (int8) kernel parity", False, f"({exc})")

    # 11 (--ssd-parity): State Space Duality — the SSD/Mamba family's
    # chunked matmul-form prefill scan against the O(1) decode
    # recurrence (the two dual forms of the same selective-SSM layer;
    # ops.ssd). The serving path keeps the recurrence for byte-identity,
    # so this parity is the gate before the matmul form serves prefill
    # on a device.
    if args.ssd_parity:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity) + 1)
        try:
            from tpu_engine.ops.ssd import ssd_parity_check

            small = ssd_parity_check()
            wide = ssd_parity_check(batch=1, seq=65, heads=8, head_dim=16,
                                    d_state=16, chunk=16, seed=3)
            worst_y = max(small["max_abs_diff_y"], wide["max_abs_diff_y"])
            worst_s = max(small["max_abs_diff_state"],
                          wide["max_abs_diff_state"])
            step(n, "SSD duality parity (matmul form vs recurrence)",
                 small["ok"] and wide["ok"],
                 f"(max|Δ| y {worst_y:.2e}, state {worst_s:.2e})")
        except Exception as exc:
            step(n, "SSD duality parity (matmul form vs recurrence)",
                 False, f"({exc})")

    # 12 (--tp-parity): tensor-parallel serving — a tp=2 continuous
    # scheduler (registry-declared param placement, H_kv-sharded pool)
    # against the single-device arm, in-process. Greedy streams must be
    # byte-identical and mixed ticks == dispatches; on a multi-chip
    # host this validates the SPMD compile the tp-ab campaign stage
    # needs before serving --tp.
    if args.tp_parity:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity)
             + int(args.ssd_parity) + 1)
        try:
            import os as _os

            if "jax" not in sys.modules and not _os.environ.get(
                    "XLA_FLAGS", ""):
                # CPU hosts: provision a 2-device virtual mesh while we
                # still can (before jax initializes). TPU hosts ignore
                # the flag; a live multi-chip backend uses real chips.
                _os.environ["XLA_FLAGS"] = (
                    "--xla_force_host_platform_device_count=2")
            import jax as _jax

            from tpu_engine.models.registry import (
                _ensure_builtin_models_imported,
                create_model,
            )
            from tpu_engine.runtime.scheduler import ContinuousGenerator

            _ensure_builtin_models_imported()
            if len(_jax.devices()) < 2:
                step(n, "tensor-parallel serving parity (tp=2 vs 1)",
                     True, "(single visible device: skipped — set "
                           "XLA_FLAGS=--xla_force_host_platform_device_"
                           "count=2 on CPU hosts)")
            else:
                tp_spec = create_model("gpt2-small-test", max_seq=64)
                tp_params = tp_spec.init(_jax.random.PRNGKey(0))
                tp_prompts = [[5, 9, 3, 17], [2, 4, 6, 8, 10, 12],
                              [1] * 20]

                def _tp_run(tp):
                    gen = ContinuousGenerator(
                        tp_spec, params=tp_params, dtype="float32",
                        n_slots=4, kv_block_size=16, prefill_chunk=16,
                        mixed_step=True, mixed_token_budget=32, tp=tp)
                    try:
                        out = gen.generate(tp_prompts, max_new_tokens=10)
                        return out, gen.stats()
                    finally:
                        gen.stop()

                ref, _ = _tp_run(1)
                sharded, st = _tp_run(2)
                m = st["mixed"]
                ok = (sharded == ref and m["ticks"] == m["dispatches"]
                      and st.get("tp", {}).get("tp") == 2)
                step(n, "tensor-parallel serving parity (tp=2 vs 1)",
                     ok,
                     f"(streams "
                     f"{'identical' if sharded == ref else 'DIVERGED'}"
                     f", ticks={m['ticks']} "
                     f"dispatches={m['dispatches']})")
        except Exception as exc:
            step(n, "tensor-parallel serving parity (tp=2 vs 1)", False,
                 f"({exc})")

    # 13 (--failover): one scripted kill/resume against a local worker
    # pair — the journal splice, live, in one line: spawn two standalone
    # workers, stream through a failover-enabled gateway, kill -9 the
    # serving lane mid-stream, and diff the spliced stream against an
    # unkilled blocking control.
    if args.failover:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity)
             + int(args.ssd_parity) + int(args.tp_parity) + 1)
        procs = []
        try:
            import signal
            import threading

            from tools.fault_injection import (
                _call,
                launch_worker_procs,
                rid_for_lane,
            )
            from tpu_engine.serving.gateway import Gateway, _parse_sse
            from tpu_engine.utils.config import GatewayConfig

            ports, procs = launch_worker_procs(2)
            gw = Gateway([f"127.0.0.1:{p}" for p in ports],
                         GatewayConfig(failover_streams=True))
            victim_lane = next(l for l in gw.worker_names()
                               if str(ports[0]) in l)
            rid = rid_for_lane(gw._ring, victim_lane, "fo")
            req = {"request_id": rid, "prompt_tokens": [5, 9, 3, 17],
                   "max_new_tokens": 24, "temperature": 0.9, "seed": 7}
            _, ctl = _call(ports[1], "POST", "/generate",
                           dict(req, request_id="ctl"), timeout=600)
            control = ctl["tokens"]
            toks, final = [], {}

            def consume():
                for frame in gw.route_generate_stream(dict(req)):
                    evt = _parse_sse(frame)
                    if evt and evt.get("done"):
                        final.update(evt)
                        break
                    if evt and "tokens" in evt:
                        toks.extend(evt["tokens"])

            t = threading.Thread(target=consume, daemon=True)
            t.start()
            import time as _time

            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline and len(toks) < 2:
                _time.sleep(0.02)
            procs[0].send_signal(signal.SIGKILL)
            procs[0].wait(timeout=10)
            t.join(timeout=300)
            gw.stop()
            spliced = final.get("tokens")
            if spliced == control and toks == control:
                detail = (f"(identical: {len(control)} tokens, "
                          f"resumed={final.get('resumed', 0)}, "
                          f"replayed="
                          f"{gw.failover.get('tokens_replayed')})")
                ok = True
            else:
                div = next((i for i, (a, b) in enumerate(
                    zip(spliced or [], control))
                    if a != b), min(len(spliced or []), len(control)))
                detail = (f"(DIVERGED at token {div}: "
                          f"spliced={spliced} control={control})")
                ok = False
            step(n, "stream kill/resume splice vs control", ok, detail)
        except Exception as exc:
            step(n, "stream kill/resume splice vs control", False,
                 f"({exc})")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()

    # (--migrate): one scripted migrate-mode drain against a local
    # worker pair — the KV block handoff, live, in one line: stream
    # through a migrate-enabled gateway, remove the serving lane with
    # drain=True, and diff the spliced stream against an unkilled
    # blocking control (zero re-prefilled tokens expected).
    if args.migrate:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity)
             + int(args.ssd_parity) + int(args.tp_parity)
             + int(args.failover) + 1)
        procs = []
        try:
            import threading

            from tools.fault_injection import (
                _call,
                launch_worker_procs,
                rid_for_lane,
            )
            from tpu_engine.serving.gateway import Gateway, _parse_sse
            from tpu_engine.utils.config import GatewayConfig

            ports, procs = launch_worker_procs(2)
            gw = Gateway([f"127.0.0.1:{p}" for p in ports],
                         GatewayConfig(failover_streams=True,
                                       migrate_streams=True,
                                       migrate_timeout_s=60.0))
            victim_lane = next(l for l in gw.worker_names()
                               if str(ports[0]) in l)
            rid = rid_for_lane(gw._ring, victim_lane, "mg")
            req = {"request_id": rid, "prompt_tokens": [5, 9, 3, 17],
                   "max_new_tokens": 24, "temperature": 0.9, "seed": 7}
            _, ctl = _call(ports[1], "POST", "/generate",
                           dict(req, request_id="ctl"), timeout=600)
            control = ctl["tokens"]
            toks, final = [], {}

            def consume():
                for frame in gw.route_generate_stream(dict(req)):
                    evt = _parse_sse(frame)
                    if evt and evt.get("done"):
                        final.update(evt)
                        break
                    if evt and "tokens" in evt:
                        toks.extend(evt["tokens"])

            t = threading.Thread(target=consume, daemon=True)
            t.start()
            import time as _time

            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline and len(toks) < 2:
                _time.sleep(0.02)
            gw.remove_worker(victim_lane, drain=True)
            t.join(timeout=300)
            mig = gw.get_stats().get("migration", {})
            gw.stop()
            spliced = final.get("tokens")
            if spliced == control and toks == control:
                detail = (f"(identical: {len(control)} tokens, "
                          f"migrated={mig.get('streams_migrated')}, "
                          f"fallbacks={mig.get('migration_fallbacks')}, "
                          f"tokens_migrated="
                          f"{mig.get('tokens_migrated')})")
                ok = mig.get("streams_migrated", 0) >= 1
            else:
                div = next((i for i, (a, b) in enumerate(
                    zip(spliced or [], control))
                    if a != b), min(len(spliced or []), len(control)))
                detail = (f"(DIVERGED at token {div}: "
                          f"spliced={spliced} control={control})")
                ok = False
            step(n, "migrate-mode drain splice vs control", ok, detail)
        except Exception as exc:
            step(n, "migrate-mode drain splice vs control", False,
                 f"({exc})")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()

    # (--disagg): one scripted prefill→decode handoff against a local
    # worker pair — the steady-state disaggregated path, live, in one
    # line: stream through a --disagg gateway (1 prefill + 1 decode
    # lane), let the KV chain hand off, and diff the spliced stream
    # against an unkilled blocking control (zero re-prefilled tokens).
    if args.disagg:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity)
             + int(args.ssd_parity) + int(args.tp_parity)
             + int(args.failover) + int(args.migrate) + 1)
        procs = []
        try:
            import threading

            from tools.fault_injection import _call, launch_worker_procs
            from tpu_engine.serving.gateway import Gateway, _parse_sse
            from tpu_engine.utils.config import GatewayConfig

            ports, procs = launch_worker_procs(
                2, per_worker_args=(("--role", "prefill"),
                                    ("--role", "decode")))
            dgw = Gateway([f"127.0.0.1:{p}" for p in ports],
                          GatewayConfig(disagg=True,
                                        handoff_timeout_s=60.0,
                                        failover_streams=True))
            req = {"request_id": "dg", "prompt_tokens": [5, 9, 3, 17],
                   "max_new_tokens": 24, "temperature": 0.9, "seed": 7}
            _, ctl = _call(ports[1], "POST", "/generate",
                           dict(req, request_id="ctl"), timeout=600)
            control = ctl["tokens"]
            toks, final = [], {}

            def consume_dg():
                for frame in dgw.route_generate_stream(dict(req)):
                    evt = _parse_sse(frame)
                    if evt and evt.get("done"):
                        final.update(evt)
                        break
                    if evt and "tokens" in evt:
                        toks.extend(evt["tokens"])

            t = threading.Thread(target=consume_dg, daemon=True)
            t.start()
            t.join(timeout=300)
            ho = dgw.get_stats().get("handoff", {})
            dgw.stop()
            spliced = final.get("tokens")
            if spliced == control and toks == control:
                detail = (f"(identical: {len(control)} tokens, "
                          f"routed={ho.get('prefill_routed')}, "
                          f"spliced={ho.get('handoffs_spliced')}, "
                          f"fallbacks={ho.get('handoff_fallbacks')})")
                ok = ho.get("handoffs_spliced", 0) >= 1
            else:
                div = next((i for i, (a, b) in enumerate(
                    zip(spliced or [], control))
                    if a != b), min(len(spliced or []), len(control)))
                detail = (f"(DIVERGED at token {div}: "
                          f"spliced={spliced} control={control})")
                ok = False
            step(n, "disagg prefill→decode handoff vs control", ok,
                 detail)
        except Exception as exc:
            step(n, "disagg prefill→decode handoff vs control", False,
                 f"({exc})")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()

    # (--overload): overload-control state, live — the gateway's
    # /stats overload block and each lane's brownout ladder stage. Works
    # whether or not the flags are on: a defaults-off deployment reports
    # "overload control off" (the additive blocks are absent), which is
    # itself the wire-compat check in one line.
    if args.overload:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity)
             + int(args.ssd_parity) + int(args.tp_parity)
             + int(args.failover) + int(args.migrate)
             + int(args.disagg) + 1)
        try:
            status, stats = _get(gw, "/stats")
            ov = stats.get("overload")
            parts = []
            if ov is None:
                parts.append("gateway overload control off")
            else:
                parts.append(
                    f"inflight {ov.get('inflight')}"
                    + (f"/{ov['max_inflight']}" if "max_inflight" in ov
                       else "")
                    + f", pressure {ov.get('pressure')}, "
                    f"sheds tier={ov.get('shed_tier')} "
                    f"depth={ov.get('shed_depth')} "
                    f"rate={ov.get('rate_limited')}")
            # Brownout stage per lane: direct worker /health, or the
            # combined front's per-lane breakdown.
            lanes = {}
            if workers:
                for w in workers:
                    try:
                        _, h = _get(w, "/health")
                        lanes[h.get("node_id", w)] = h.get("brownout")
                    except Exception:
                        lanes[w] = None
            else:
                _, h = _get(gw, "/health")
                for node, lane_h in (h.get("lanes") or {}).items():
                    lanes[node] = lane_h.get("brownout")
            if any(b for b in lanes.values()):
                parts.append("brownout " + ", ".join(
                    f"{node}:{(b or {}).get('stage_name', 'off')}"
                    f"[{(b or {}).get('stage', '-')}]"
                    for node, b in sorted(lanes.items())))
            else:
                parts.append("brownout off on all lanes")
            step(n, "overload control state", status == 200,
                 "(" + "; ".join(parts) + ")")
        except Exception as exc:
            step(n, "overload control state", False, f"({exc})")

    # 17 (--elastic): elastic-fleet state of the live system — the
    # /admin/fleet status surface: membership, NAMED degraded states
    # (spawn-wedged / drain-wedged), whether the closed loop is
    # engaged, the last observed fleet pressure, and the decision
    # counters. A static fleet answers too (controller unstarted,
    # counters zero) — that is the defaults-off wire-compat check.
    if args.elastic:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity)
             + int(args.ssd_parity) + int(args.tp_parity)
             + int(args.failover) + int(args.migrate)
             + int(args.disagg) + int(args.overload) + 1)
        try:
            status, fleet = _post(gw, "/admin/fleet",
                                  {"action": "status"})
            parts = [f"state {fleet.get('state')}",
                     f"{len(fleet.get('lanes') or [])} lanes",
                     "autoscale "
                     + ("on" if fleet.get("autoscale") else "off")]
            if fleet.get("pressure") is not None:
                parts.append(f"pressure {fleet['pressure']}")
            ctr = fleet.get("counters") or {}
            acted = {k: v for k, v in ctr.items() if v}
            parts.append("decisions " + (", ".join(
                f"{k}={v}" for k, v in sorted(acted.items()))
                or "none yet"))
            for lane, reason in sorted(
                    (fleet.get("degraded") or {}).items()):
                parts.append(f"DEGRADED {lane}:{reason}")
            step(n, "elastic fleet state",
                 status == 200 and bool(fleet.get("ok")),
                 "(" + "; ".join(parts) + ")")
        except Exception as exc:
            step(n, "elastic fleet state", False, f"({exc})")

    # (--stitch): one scripted cross-lane stitched trace — the
    # observability-plane smoke, live, in one line: drive a stream
    # through a --trace-stitch gateway over a spawned worker pair,
    # drain-migrate it to the other lane mid-generation, then render
    # the merged /admin/trace/<request_id> tree. The stream must land
    # byte-identical to an unmoved control AND the stitched tree must
    # cover both lanes with zero orphaned spans.
    if args.stitch:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity)
             + int(args.ssd_parity) + int(args.tp_parity)
             + int(args.failover) + int(args.migrate)
             + int(args.disagg) + int(args.overload)
             + int(args.elastic) + 1)
        procs = []
        try:
            import threading

            from tools.fault_injection import (
                _call,
                launch_worker_procs,
                rid_for_lane,
            )
            from tpu_engine.serving.gateway import Gateway, _parse_sse
            from tpu_engine.utils.config import GatewayConfig

            ports, procs = launch_worker_procs(
                2, per_worker_args=(("--trace-stitch",),
                                    ("--trace-stitch",)))
            sgw = Gateway([f"127.0.0.1:{p}" for p in ports],
                          GatewayConfig(failover_streams=True,
                                        migrate_streams=True,
                                        migrate_timeout_s=60.0,
                                        trace_stitch=True))
            victim_lane = next(l for l in sgw.worker_names()
                               if str(ports[0]) in l)
            rid = rid_for_lane(sgw._ring, victim_lane, "st")
            req = {"request_id": rid, "prompt_tokens": [5, 9, 3, 17],
                   "max_new_tokens": 24, "temperature": 0.9, "seed": 7}
            _, ctl = _call(ports[1], "POST", "/generate",
                           dict(req, request_id="ctl"), timeout=600)
            control = ctl["tokens"]
            toks, final = [], {}

            def consume_st():
                for frame in sgw.route_generate_stream(dict(req)):
                    evt = _parse_sse(frame)
                    if evt and evt.get("done"):
                        final.update(evt)
                        break
                    if evt and "tokens" in evt:
                        toks.extend(evt["tokens"])

            t = threading.Thread(target=consume_st, daemon=True)
            t.start()
            import time as _time

            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline and len(toks) < 2:
                _time.sleep(0.02)
            sgw.remove_worker(victim_lane, drain=True)
            t.join(timeout=300)
            stitched = sgw.stitched_trace(rid)
            sgw.stop()
            spliced = final.get("tokens")
            lanes = stitched.get("lanes") or []
            spans = stitched.get("spans") or []
            orphans = stitched.get("orphans", -1)
            hops = stitched.get("hops") or []
            hop_kinds = ",".join(h.get("kind", "?") for h in hops)
            if spliced == control and toks == control:
                detail = (f"({len(control)} tokens identical; "
                          f"{len(lanes)} lanes {lanes}, "
                          f"{len(spans)} spans, orphans={orphans}, "
                          f"hops=[{hop_kinds}])")
                ok = len(lanes) >= 2 and orphans == 0 and len(hops) >= 2
            else:
                div = next((i for i, (a, b) in enumerate(
                    zip(spliced or [], control))
                    if a != b), min(len(spliced or []), len(control)))
                detail = (f"(DIVERGED at token {div}: "
                          f"spliced={spliced} control={control})")
                ok = False
            step(n, "cross-lane stitched trace", ok, detail)
        except Exception as exc:
            step(n, "cross-lane stitched trace", False, f"({exc})")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()

    # (--fleet-prefix): one scripted owner→peer KV prefix fetch — the
    # fleet prefix tier's smoke, live, in one line: lane 0 serves a
    # shared 48-token prefix (becoming its directory owner), then a
    # request landing on lane 1 carries the gateway's peer hint and
    # must SPLICE the owner's chain over HTTP instead of re-prefilling
    # it, byte-identical to an unhinted control.
    if args.fleet_prefix:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity)
             + int(args.ssd_parity) + int(args.tp_parity)
             + int(args.failover) + int(args.migrate)
             + int(args.disagg) + int(args.overload)
             + int(args.elastic) + int(args.stitch) + 1)
        procs = []
        try:
            from tools.fault_injection import (
                _call,
                launch_worker_procs,
                rid_for_lane,
                victim_lane_for_port,
            )
            from tpu_engine.serving.gateway import Gateway
            from tpu_engine.utils.config import GatewayConfig

            ports, procs = launch_worker_procs(
                2, extra_args=("--prefix-fetch",))
            pgw = Gateway([f"127.0.0.1:{p}" for p in ports],
                          GatewayConfig(prefix_directory=True))
            lanes = pgw.worker_names()
            shared = [(17 * j + 5) % 97 + 1 for j in range(48)]
            own_rid = rid_for_lane(
                pgw._ring, victim_lane_for_port(lanes, ports[0]), "fpd_o")
            fetch_rid = rid_for_lane(
                pgw._ring, victim_lane_for_port(lanes, ports[1]), "fpd_f")
            own = pgw.route_generate(
                {"request_id": own_rid, "prompt_tokens": shared + [3, 1],
                 "max_new_tokens": 8})
            fetch_req = {"request_id": fetch_rid,
                         "prompt_tokens": shared + [5, 2],
                         "max_new_tokens": 8}
            _, ctl = _call(ports[0], "POST", "/generate",
                           dict(fetch_req, request_id="fpd_ctl"),
                           timeout=600)
            fetched = pgw.route_generate(dict(fetch_req))
            _, health = _call(ports[1], "GET", "/health", timeout=10)
            fs = (health.get("generator") or {}).get("prefix_fetch") or {}
            pd = pgw.get_stats().get("prefix_directory", {})
            pgw.stop()
            identical = fetched["tokens"] == ctl["tokens"]
            ok = (identical and bool(own.get("tokens"))
                  and fs.get("attempted") == 1 and fs.get("spliced") == 1
                  and fs.get("blocks_spliced", 0) >= 3
                  and pd.get("hints_attached", 0) >= 1)
            step(n, "fleet prefix fetch", ok,
                 f"({fs.get('blocks_spliced', 0)} blocks spliced, "
                 f"{fs.get('prefill_tokens_skipped_remote', 0)} remote "
                 f"prefill tokens skipped, "
                 f"{pd.get('hints_attached', 0)} hints attached, "
                 f"{pd.get('entries', 0)} directory entries; "
                 f"{'byte-identical' if identical else 'DIVERGED'})")
        except Exception as exc:
            step(n, "fleet prefix fetch", False, f"({exc})")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()

    if args.unified:
        n = (6 + int(args.kernel_parity) + int(args.mixed_parity)
             + int(args.spec_parity) + int(args.quant_parity)
             + int(args.ssd_parity) + int(args.tp_parity)
             + int(args.failover) + int(args.migrate)
             + int(args.disagg) + int(args.overload)
             + int(args.elastic) + int(args.stitch)
             + int(args.fleet_prefix) + 1)
        try:
            import threading as _threading

            from tpu_engine.serving.worker import WorkerNode
            from tpu_engine.utils.config import WorkerConfig

            uw = WorkerNode(WorkerConfig(
                node_id="diag_u", model="gpt2-small-test",
                dtype="float32", max_batch_size=4))
            try:
                score_req = {"prompt_tokens": [1, 2, 3],
                             "completion_tokens": [4, 5]}
                control = uw.handle_score(
                    dict(score_req, request_id="du_ctl"))
                base = uw.generator.stats()["stateless"]["dispatches"]
                # Live mixed-tick watcher: sample the scheduler while
                # the workload runs and keep the first snapshot where
                # decode rows are resident AND a one-shot dispatch has
                # landed since the watch began — the mixed-row tick,
                # caught in the act.
                live: dict = {}
                stop_w = _threading.Event()

                def watch():
                    while not stop_w.is_set():
                        st = uw.generator.stats()
                        sl = st.get("stateless", {})
                        if (st.get("active", 0) > 0
                                and sl.get("dispatches", 0) > base
                                and not live):
                            live.update(
                                decode_rows=st["active"],
                                oneshot_dispatches=(sl["dispatches"]
                                                    - base),
                                score_rows=sl.get("score_rows", 0))
                        time.sleep(0.002)

                results: dict = {}

                def drive_gen():
                    results["g"] = uw.handle_generate(
                        {"request_id": "du_g",
                         "prompt_tokens": [1, 2, 3, 4],
                         "max_new_tokens": 24})

                def drive_score(i):
                    results[f"s{i}"] = uw.handle_score(
                        dict(score_req, request_id=f"du_s{i}"))

                wt = _threading.Thread(target=watch, daemon=True)
                wt.start()
                gt = _threading.Thread(target=drive_gen)
                gt.start()
                time.sleep(0.05)  # let the stream take residency
                sts = [_threading.Thread(target=drive_score, args=(i,))
                       for i in range(3)]
                for t in sts:
                    t.start()
                for t in [gt] + sts:
                    t.join()
                stop_w.set()
                wt.join(timeout=5)
                sl = uw.generator.stats()["stateless"]
                identical = all(
                    results[f"s{i}"]["logprobs"] == control["logprobs"]
                    for i in range(3))
                ticks_ok = sl["ticks"] == sl["dispatches"]
                ok = (bool(live) and identical and ticks_ok
                      and sl["failed"] == 0)
                step(n, "unified mixed-row tick", ok,
                     f"({live.get('decode_rows', 0)} decode rows beside "
                     f"{live.get('oneshot_dispatches', 0)} one-shot "
                     f"dispatch(es), {sl.get('score_rows', 0)} score "
                     f"rows total; ticks==dispatches "
                     f"{'holds' if ticks_ok else 'VIOLATED'}; scores "
                     f"{'byte-identical' if identical else 'DIVERGED'})")
            finally:
                uw.stop()
        except Exception as exc:
            step(n, "unified mixed-row tick", False, f"({exc})")

    # 12 (--lint): the engine-lint suite, in-process — the same gate
    # tier-1 runs (tests/test_engine_lint.py), surfaced here so an
    # operator can check a working tree before pushing.
    if args.lint:
        n = _TOTAL  # always the last step
        try:
            from tools.analyze import baseline as lint_baseline
            from tools.analyze import run_suite

            report = run_suite()
            new, old = lint_baseline.split(report.findings)
            counts = {}
            for f in new:
                counts[f.rule] = counts.get(f.rule, 0) + 1
            summary = (", ".join(f"{r}={c}" for r, c in sorted(
                counts.items())) or "no findings")
            step(n, "engine-lint static analysis", not new,
                 f"({summary}; {len(old)} baselined, "
                 f"{len(report.waived)} waived)")
            for f in new:
                print(f"      {f.format()}")
        except Exception as exc:
            step(n, "engine-lint static analysis", False, f"({exc})")

    n_ok = sum(_results)
    print(f"\n{n_ok}/{len(_results)} checks passed")
    return 0 if n_ok == len(_results) else 1


if __name__ == "__main__":
    sys.exit(main())
