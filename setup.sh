#!/usr/bin/env bash
# Environment bootstrap — the reference's setup.sh (pacman + vendored libs)
# equivalent. Nothing to download here (jax/flax/optax/orbax and the C++
# toolchain are baked into the image); this script builds the native core
# and smoke-checks the install.
set -euo pipefail
cd "$(dirname "$0")"

echo "== tpu_engine setup =="

# 1. Native C++ core (LRU cache, hash ring, breaker, batch queue).
if command -v cmake >/dev/null && command -v ninja >/dev/null; then
    cmake -S tpu_engine/native -B build/native -G Ninja >/dev/null
    ninja -C build/native >/dev/null
    cp build/native/libtpucore.so tpu_engine/native/libtpucore.so
    echo "[1/3] native core built (cmake+ninja)"
else
    bash tpu_engine/native/build.sh >/dev/null
    echo "[1/3] native core built (g++ direct)"
fi

# 2. Python deps present?
python - <<'EOF'
import jax, flax, optax, orbax.checkpoint  # noqa: F401
print("[2/3] python deps ok (jax", jax.__version__ + ")")
EOF

# 3. Smoke: native bindings load + one CPU-mesh forward.
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
from tpu_engine.core import native
from tpu_engine.models.registry import create_model, _ensure_builtin_models_imported
_ensure_builtin_models_imported()
spec = create_model("mlp")
params = spec.init(jax.random.PRNGKey(0))
out = spec.apply(params, jax.numpy.ones((1, spec.input_size)))
assert out.shape[0] == 1
print(f"[3/3] smoke ok (native core: {'loaded' if native.available() else 'python fallback'})")
EOF

echo "setup complete — try: python -m tpu_engine.serving.cli serve --model resnet50"
