#!/usr/bin/env python3
"""End-to-end serving benchmark — the reference's headline harness, reproduced.

Mirrors /root/reference/benchmark.py: a closed-loop multithreaded client
POSTs `{request_id, input_data}` JSON to the gateway `/infer` endpoint
(10,000 requests, 50 threads, 10 distinct input vectors — the reference's
published 522.64 req/s run, README.md:274-300). The serving stack under
test is the TPU-native combined process: HTTP front door → hash-ring lane
selection → LRU cache → dynamic batcher → shape-bucketed XLA executables.

The server runs in a SEPARATE process (its own GIL) so the client load
generator doesn't share an interpreter with the serving path.

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
All progress/diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import socket
import statistics
import subprocess
import sys
import threading
import time

BASELINE_REQ_S = 522.64  # reference README.md:283 (BASELINE.md)
REPO = os.path.dirname(os.path.abspath(__file__))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_ready(port: int, timeout_s: float = 600.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            conn.request("GET", "/stats")
            resp = conn.getresponse()
            resp.read()
            conn.close()
            if resp.status == 200:
                return
        except OSError:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"server on port {port} not ready after {timeout_s}s")


class LoadGen:
    """Closed-loop load: T threads, each a persistent keep-alive connection,
    issuing its share of N requests back-to-back (reference benchmark.py:49-76)."""

    def __init__(self, port: int, n_requests: int, n_threads: int,
                 distinct_inputs: int = 10):
        self.port = port
        self.n_requests = n_requests
        self.n_threads = n_threads
        # Reference workload: input cycles through 10 distinct small vectors
        # (benchmark.py:23) — the ~99.7% cache hit rate is a workload property.
        self.payloads = [
            json.dumps({
                "request_id": "req_{}",  # filled per request
                "input_data": [float(i), float(i + 1), float(i + 2)],
            })
            for i in range(distinct_inputs)
        ]
        self.latencies_ms: list[list[float]] = [[] for _ in range(n_threads)]
        self.failures = [0] * n_threads

    def _worker(self, tid: int, start_idx: int, count: int) -> None:
        lat = self.latencies_ms[tid]
        conn = http.client.HTTPConnection("127.0.0.1", self.port, timeout=30)
        headers = {"Content-Type": "application/json"}
        for k in range(count):
            i = start_idx + k
            body = self.payloads[i % len(self.payloads)].replace(
                '"req_{}"', f'"req_{i}"')
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/infer", body=body, headers=headers)
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            except (OSError, http.client.HTTPException):
                ok = False
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", self.port, timeout=30)
            dt_ms = (time.perf_counter() - t0) * 1e3
            if ok:
                lat.append(dt_ms)
            else:
                self.failures[tid] += 1
        conn.close()

    def run(self) -> dict:
        per = self.n_requests // self.n_threads
        extra = self.n_requests % self.n_threads
        threads = []
        idx = 0
        t_start = time.perf_counter()
        for tid in range(self.n_threads):
            count = per + (1 if tid < extra else 0)
            th = threading.Thread(target=self._worker, args=(tid, idx, count))
            idx += count
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        wall_s = time.perf_counter() - t_start
        lats = sorted(x for chunk in self.latencies_ms for x in chunk)
        n_ok = len(lats)
        n_fail = sum(self.failures)

        def pct(p: float) -> float:
            if not lats:
                return 0.0
            return lats[min(len(lats) - 1, int(p / 100.0 * len(lats)))]

        return {
            "requests": self.n_requests,
            "success": n_ok,
            "failed": n_fail,
            "success_rate": n_ok / max(1, self.n_requests),
            "wall_s": round(wall_s, 3),
            "throughput_req_s": round(n_ok / wall_s, 2) if wall_s > 0 else 0.0,
            "latency_ms": {
                "mean": round(statistics.fmean(lats), 3) if lats else 0.0,
                "p50": round(pct(50), 3),
                "p90": round(pct(90), 3),
                "p95": round(pct(95), 3),
                "p99": round(pct(99), 3),
                "max": round(lats[-1], 3) if lats else 0.0,
            },
        }


def scrape_stats(port: int) -> dict:
    out = {}
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/health")
        resp = conn.getresponse()
        health = json.loads(resp.read())
        conn.close()
        out["cache_hit_rate"] = health.get("cache_hit_rate")
        bp = health.get("batch_processor", {})
        out["avg_batch_size"] = bp.get("avg_batch_size")
    except Exception as exc:  # stats are best-effort
        log(f"stats scrape failed: {exc}")
    return out


def launch_server(model: str, port: int, lanes: int) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "tpu_engine.serving.cli", "serve",
           "--model", model, "--port", str(port), "--lanes", str(lanes),
           "--warmup"]
    log(f"launching server: {' '.join(cmd)}")
    return subprocess.Popen(cmd, cwd=REPO, env=env,
                            stdout=sys.stderr, stderr=sys.stderr)


def run_cache_test(port: int, n: int = 100) -> dict:
    """Reference benchmark.py's cache-effectiveness A/B (its :180-220):
    n distinct inputs (miss phase), then the same n again (hit phase)."""
    import random

    rnd = random.Random(1234)
    inputs = [[rnd.uniform(0, 100) for _ in range(3)] for _ in range(n)]

    def phase(tag):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        lats = []
        for i, vec in enumerate(inputs):
            body = json.dumps({"request_id": f"cache_{tag}_{i}",
                               "input_data": vec})
            t0 = time.perf_counter()
            conn.request("POST", "/infer", body=body,
                         headers={"Content-Type": "application/json"})
            conn.getresponse().read()
            lats.append((time.perf_counter() - t0) * 1e3)
        conn.close()
        return statistics.fmean(lats)

    # Same request_id per input across phases so both route to one lane.
    miss_ms = phase("x")
    hit_ms = phase("x")
    return {
        "miss_avg_ms": round(miss_ms, 3),
        "hit_avg_ms": round(hit_ms, 3),
        "speedup": round(miss_ms / max(hit_ms, 1e-9), 2),
    }


def run_generate_bench(port: int, n_requests: int = 16, max_new: int = 32,
                       n_threads: int = 8) -> dict:
    """Autoregressive decode throughput: concurrent /generate requests,
    reports generated tokens/s (BASELINE config 5 workload)."""
    import random

    rnd = random.Random(7)
    prompts = [[rnd.randrange(1, 200) for _ in range(rnd.randrange(4, 24))]
               for _ in range(n_requests)]
    tokens_out = [0] * n_threads
    fails = [0] * n_threads

    def worker(tid):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        for i in range(tid, n_requests, n_threads):
            body = json.dumps({"request_id": f"gen_{i}",
                               "prompt_tokens": prompts[i],
                               "max_new_tokens": max_new})
            try:
                conn.request("POST", "/generate", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = json.loads(resp.read())
                if resp.status == 200:
                    tokens_out[tid] += len(data["tokens"])
                else:
                    fails[tid] += 1
            except (OSError, http.client.HTTPException):
                fails[tid] += 1
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        conn.close()

    # Warm the compiled prefill/decode executables before timing.
    warm = threading.Thread(target=worker, args=(0,))
    warm.start()
    warm.join()
    tokens_out[0] = 0

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(tid,))
               for tid in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total = sum(tokens_out)
    return {
        "tokens": total,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total / wall, 2) if wall > 0 else 0.0,
        "failed": sum(fails),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=10_000)
    ap.add_argument("--threads", type=int, default=50)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--lanes", type=int, default=0,
                    help="serving lanes (0 = one per device)")
    ap.add_argument("--port", type=int, default=0,
                    help="use an already-running server on this port")
    ap.add_argument("--quick", action="store_true",
                    help="1000 requests / 20 threads smoke run")
    ap.add_argument("--cache-test", action="store_true",
                    help="reference cache-effectiveness A/B instead of load")
    ap.add_argument("--scenario", choices=["infer", "generate"],
                    default="infer")
    args = ap.parse_args()
    if args.quick:
        args.requests, args.threads = 1000, 20
    if args.scenario == "generate" and args.model == "resnet50":
        args.model = "gpt2"

    proc = None
    port = args.port
    try:
        if port == 0:
            port = free_port()
            proc = launch_server(args.model, port, args.lanes)
        log(f"waiting for server on :{port} ...")
        wait_ready(port)

        if args.cache_test:
            result = run_cache_test(port)
            log(json.dumps(result, indent=2))
            print(json.dumps({
                "metric": "cache_speedup", "value": result["speedup"],
                "unit": "x", "vs_baseline": None, "model": args.model,
                **result,
            }), flush=True)
            return 0

        if args.scenario == "generate":
            result = run_generate_bench(port)
            log(json.dumps(result, indent=2))
            print(json.dumps({
                "metric": "decode_throughput", "value": result["tokens_per_s"],
                "unit": "tokens/s", "vs_baseline": None, "model": args.model,
                **result,
            }), flush=True)
            return 0 if result["failed"] == 0 else 1

        log("server ready; warmup pass (misses populate the cache) ...")
        warm = LoadGen(port, 20, 4)
        warm.run()

        log(f"benchmark: {args.requests} requests, {args.threads} threads")
        gen = LoadGen(port, args.requests, args.threads)
        result = gen.run()
        result.update(scrape_stats(port))
        log(json.dumps(result, indent=2))

        line = {
            "metric": "serving_throughput",
            "value": result["throughput_req_s"],
            "unit": "req/s",
            "vs_baseline": round(result["throughput_req_s"] / BASELINE_REQ_S, 3),
            "model": args.model,
            "requests": args.requests,
            "threads": args.threads,
            "success_rate": round(result["success_rate"], 4),
            "p50_ms": result["latency_ms"]["p50"],
            "p99_ms": result["latency_ms"]["p99"],
            "cache_hit_rate": result.get("cache_hit_rate"),
            "avg_batch_size": result.get("avg_batch_size"),
        }
        print(json.dumps(line), flush=True)
        return 0 if result["success_rate"] > 0.99 else 1
    finally:
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
