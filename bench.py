#!/usr/bin/env python3
"""End-to-end serving benchmark — the reference's headline harness, reproduced.

Mirrors /root/reference/benchmark.py: a closed-loop multithreaded client
POSTs `{request_id, input_data}` JSON to the gateway `/infer` endpoint
(10,000 requests, 50 threads, 10 distinct input vectors — the reference's
published 522.64 req/s run, README.md:274-300). The serving stack under
test is the TPU-native combined process: HTTP front door → hash-ring lane
selection → LRU cache → dynamic batcher → shape-bucketed XLA executables.

The server runs in a SEPARATE process (its own GIL) so the client load
generator doesn't share an interpreter with the serving path.

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
All progress/diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import socket
import statistics
import subprocess
import sys
import threading
import time
from typing import Optional, Sequence, Tuple

BASELINE_REQ_S = 522.64  # reference README.md:283 (BASELINE.md)
REPO = os.path.dirname(os.path.abspath(__file__))

# Peak dense bf16 FLOP/s per chip, by device_kind substring (public specs).
# MFU figures are computed against these; unknown chips report raw FLOP/s.
PEAK_BF16_FLOPS = (
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v6e", 918e12), ("trillium", 918e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)


def chip_peak_flops() -> tuple:
    """(device_kind, peak bf16 FLOP/s or None)."""
    import jax

    kind = jax.devices()[0].device_kind
    lk = kind.lower()
    for sub, peak in PEAK_BF16_FLOPS:
        if sub in lk:
            return kind, peak
    return kind, None


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# Every completed sub-measurement lands here AND in a RUN-STAMPED
# partial artifact immediately — so a tunnel wedge mid-run (the r2/r4
# failure mode: the driver kills the hung process and records only
# rc=1) still leaves every number measured before the wedge, both on
# disk and attached to the error JSON line main() prints. Mirrors
# tools/onchip_campaign.py's save-after-every-stage discipline.
# Run-stamped (scenario + timestamp + pid) so concurrent runs never
# clobber each other, and REMOVED on a completed run — only aborted
# runs leave a partial behind (a stale fixed-name BENCH_partial.json
# used to sit at the repo root forever).
_PARTIAL: dict = {}
_PARTIAL_PATH = None  # set on first write (run-stamped)


def _partial_path() -> str:
    global _PARTIAL_PATH
    if _PARTIAL_PATH is None:
        stamp = time.strftime("%Y%m%d_%H%M%S")
        _PARTIAL_PATH = os.path.join(
            REPO, f"BENCH_partial.{_SCENARIO}.{stamp}.{os.getpid()}.json")
    return _PARTIAL_PATH


def record_partial(name: str, data) -> None:
    _PARTIAL[name] = data
    _PARTIAL["ts"] = time.strftime("%Y-%m-%d %H:%M:%S")
    try:
        with open(_partial_path(), "w") as f:
            json.dump(_PARTIAL, f, indent=2)
    except OSError as exc:  # a read-only checkout must not kill the bench
        log(f"partial artifact write failed: {exc}")


def cleanup_partial() -> None:
    """Remove this run's partial artifact — called once the run emitted
    its final line (an ABORTED run keeps its partials for forensics)."""
    if _PARTIAL_PATH is not None and os.path.exists(_PARTIAL_PATH):
        try:
            os.remove(_PARTIAL_PATH)
        except OSError:
            pass


def free_port() -> int:
    from tpu_engine.utils.net import free_port as _fp

    return _fp()


def wait_ready(port: int, timeout_s: float = 600.0, proc=None) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            # Server died before listening — most commonly the free_port()
            # probe-then-close race (utils/net.py documents it: another
            # process can bind the probed port first). Distinct error type
            # so launch_ready retries with a FRESH port instead of
            # polling a corpse for 10 minutes.
            raise ChildProcessError(
                f"server exited rc={proc.returncode} before ready")
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            conn.request("GET", "/stats")
            resp = conn.getresponse()
            resp.read()
            conn.close()
            if resp.status == 200:
                return
        except OSError:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"server on port {port} not ready after {timeout_s}s")


class LoadGen:
    """Closed-loop load: T threads, each a persistent keep-alive connection,
    issuing its share of N requests back-to-back (reference benchmark.py:49-76).

    The client is raw sockets with precomputed request bytes — http.client's
    per-request object churn was the measured bottleneck at >8k req/s (the
    server's hit path is GIL-free C++, so client CPU directly caps the
    recorded number). Semantics unchanged: one outstanding request per
    thread, no pipelining."""

    def __init__(self, port: int, n_requests: int, n_threads: int,
                 distinct_inputs: int = 10, input_offset: int = 0):
        self.port = port
        self.n_requests = n_requests
        self.n_threads = n_threads
        # Reference workload: input cycles through 10 distinct small vectors
        # (benchmark.py:23) — the ~99.7% cache hit rate is a workload property.
        # Stored as (head, tail) byte fragments: request i's body is
        # head + str(i) + tail, with Content-Length patched per request.
        # `input_offset` shifts the vectors into a disjoint numeric range —
        # a warm-up pass must not pre-populate the cache with the measured
        # run's inputs (the cache keys on input bytes alone).
        self._frags = []
        for i in range(input_offset, input_offset + distinct_inputs):
            body = json.dumps({
                "request_id": "req_@",
                "input_data": [float(i), float(i + 1), float(i + 2)],
            })
            head, tail = body.split("req_@")
            self._frags.append((head.encode() + b"req_", tail.encode()))
        self.latencies_ms: list[list[float]] = [[] for _ in range(n_threads)]
        self.failures = [0] * n_threads

    def _connect(self) -> socket.socket:
        s = socket.create_connection(("127.0.0.1", self.port), timeout=30)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _worker(self, tid: int, start_idx: int, count: int) -> None:
        lat = self.latencies_ms[tid]
        lat_append = lat.append
        perf = time.perf_counter
        frags = self._frags
        n_frags = len(frags)
        prefix = (b"POST /infer HTTP/1.1\r\nHost: b\r\n"
                  b"Content-Type: application/json\r\nContent-Length: ")
        sock = self._connect()
        buf = b""
        for k in range(count):
            i = start_idx + k
            head, tail = frags[i % n_frags]
            ib = str(i).encode()
            body = head + ib + tail
            req = prefix + str(len(body)).encode() + b"\r\n\r\n" + body
            t0 = perf()
            try:
                sock.sendall(req)
                # Headers (server always sends Content-Length, no chunking).
                while True:
                    j = buf.find(b"\r\n\r\n")
                    if j >= 0:
                        break
                    chunk = sock.recv(65536)
                    if not chunk:
                        raise OSError("connection closed")
                    buf += chunk
                cl_at = buf.find(b"Content-Length: ", 0, j)
                cl_end = buf.find(b"\r\n", cl_at)
                total = j + 4 + int(buf[cl_at + 16:cl_end])
                while len(buf) < total:
                    chunk = sock.recv(65536)
                    if not chunk:
                        raise OSError("connection closed")
                    buf += chunk
                ok = buf.startswith(b"HTTP/1.1 200")
                buf = buf[total:]
            except (OSError, ValueError):
                ok = False
                buf = b""
                try:
                    sock.close()
                except OSError:
                    pass
                try:
                    sock = self._connect()
                except OSError:
                    pass
            if ok:
                lat_append((perf() - t0) * 1e3)
            else:
                self.failures[tid] += 1
        sock.close()

    def run(self) -> dict:
        per = self.n_requests // self.n_threads
        extra = self.n_requests % self.n_threads
        threads = []
        idx = 0
        t_start = time.perf_counter()
        for tid in range(self.n_threads):
            count = per + (1 if tid < extra else 0)
            th = threading.Thread(target=self._worker, args=(tid, idx, count))
            idx += count
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        wall_s = time.perf_counter() - t_start
        lats = sorted(x for chunk in self.latencies_ms for x in chunk)
        n_ok = len(lats)
        n_fail = sum(self.failures)

        def pct(p: float) -> float:
            if not lats:
                return 0.0
            return lats[min(len(lats) - 1, int(p / 100.0 * len(lats)))]

        return {
            "requests": self.n_requests,
            "success": n_ok,
            "failed": n_fail,
            "success_rate": n_ok / max(1, self.n_requests),
            "wall_s": round(wall_s, 3),
            "throughput_req_s": round(n_ok / wall_s, 2) if wall_s > 0 else 0.0,
            "latency_ms": {
                "mean": round(statistics.fmean(lats), 3) if lats else 0.0,
                "p50": round(pct(50), 3),
                "p90": round(pct(90), 3),
                "p95": round(pct(95), 3),
                "p99": round(pct(99), 3),
                "max": round(lats[-1], 3) if lats else 0.0,
            },
        }


def scrape_stats(port: int) -> dict:
    out = {}
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/health")
        resp = conn.getresponse()
        health = json.loads(resp.read())
        conn.close()
        out["cache_hit_rate"] = health.get("cache_hit_rate")
        bp = health.get("batch_processor", {})
        out["avg_batch_size"] = bp.get("avg_batch_size")
    except Exception as exc:  # stats are best-effort
        log(f"stats scrape failed: {exc}")
    return out


def scrape_trace_stages(port: int) -> Optional[dict]:
    """Per-stage latency attribution from the server's tracing layer
    (GET /trace "stages"): where did the wall time go — queue wait,
    batch formation, device compute, serialization? Emitted into the
    BENCH json so the perf trajectory carries attributable numbers, not
    just end-to-end req/s. Count-weighted means aggregate across lanes;
    per-stage p99 reports the worst lane (cross-lane percentiles cannot
    be merged from summaries)."""
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/trace")
        resp = conn.getresponse()
        trace = json.loads(resp.read())
        conn.close()
    except Exception as exc:  # tracing scrape is best-effort
        log(f"trace scrape failed: {exc}")
        return None
    stages = trace.get("stages")
    if not stages:
        return None
    agg: dict = {}
    for lane_stages in stages.values():
        for op, s in lane_stages.items():
            a = agg.setdefault(op, {"count": 0, "_sum": 0.0, "p99_us": 0})
            a["count"] += s["count"]
            a["_sum"] += s["mean_us"] * s["count"]
            a["p99_us"] = max(a["p99_us"], s["p99_us"])
    out = {"stages": {}}
    for op, a in sorted(agg.items()):
        out["stages"][op] = {
            "count": a["count"],
            "mean_us": round(a["_sum"] / max(1, a["count"]), 1),
            "p99_us": a["p99_us"],
        }
    qw = out["stages"].get("queue_wait")
    dc = out["stages"].get("device_compute")
    if qw and dc and dc["mean_us"] > 0:
        # The headline attribution ratio: >1 means requests spend longer
        # waiting for a batch slot than computing — batching policy, not
        # the device, is the next thing to tune.
        out["queue_wait_vs_device_compute"] = round(
            qw["mean_us"] / dc["mean_us"], 3)
    return out


def stop_server(proc: Optional[subprocess.Popen]) -> None:
    """terminate -> bounded wait -> kill; shared by every launcher site."""
    if proc is None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def launch_server(model: str, port: int, lanes: int,
                  mixed: bool = False,
                  pipeline_depth: Optional[int] = None,
                  batch_buckets: Optional[str] = None) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "tpu_engine.serving.cli", "serve",
           "--model", model, "--port", str(port), "--lanes", str(lanes),
           "--warmup"]
    if mixed:
        cmd += ["--shape-buckets", "320x320x3,480x480x3,640x640x3"]
    if pipeline_depth is not None:
        cmd += ["--pipeline-depth", str(pipeline_depth)]
    if batch_buckets is not None:
        cmd += ["--batch-buckets", batch_buckets]
    log(f"launching server: {' '.join(cmd)}")
    return subprocess.Popen(cmd, cwd=REPO, env=env,
                            stdout=sys.stderr, stderr=sys.stderr)


def launch_ready(model: str, lanes: int, attempts: int = 3,
                 **launch_kw) -> Tuple[int, subprocess.Popen]:
    """Pick a free port, launch, wait ready — retrying the WHOLE pick+
    launch on an early exit. free_port() can only probe: the kernel may
    hand the same port to another process between the probe close and the
    server's bind, so the consumer (here), not the prober, owns the
    retry."""
    last: Exception = RuntimeError("unreachable")
    for attempt in range(attempts):
        port = free_port()
        proc = launch_server(model, port, lanes, **launch_kw)
        try:
            wait_ready(port, proc=proc)
            return port, proc
        except ChildProcessError as exc:
            last = exc
            log(f"launch attempt {attempt + 1}/{attempts} failed ({exc}); "
                "retrying on a fresh port")
        except BaseException:
            stop_server(proc)
            raise
    raise RuntimeError(f"server failed to launch after {attempts} "
                       f"attempts: {last}")


def run_miss_path_sweep(model: str = "resnet50",
                        depths: Sequence[int] = (4, 8, 16),
                        n_requests: int = 3000, n_threads: int = 50) -> dict:
    """Miss-path (all-distinct inputs, zero cache hits) throughput vs
    submit/collect pipeline depth (VERDICT r4 item 3: 15.6 ms/b32 against
    5.3 ms device — if the gap is un-overlapped tunnel round-trips, deeper
    pipelining closes it; if it is host work, it won't). Full HTTP serving
    path, one server process per depth."""
    out: dict = {"model": model, "n_requests": n_requests,
                 "threads": n_threads}
    for depth in depths:
        port, proc = launch_ready(model, 0, pipeline_depth=depth)
        try:
            # Warm in a DISJOINT input range: warm vectors in the cache
            # would serve the measured run's first requests as hits.
            LoadGen(port, 200, 8, distinct_inputs=200,
                    input_offset=10_000_000).run()
            r = LoadGen(port, n_requests, n_threads,
                        distinct_inputs=n_requests).run()
            out[f"depth{depth}"] = {
                "throughput_req_s": r["throughput_req_s"],
                "p50_ms": r["latency_ms"]["p50"],
                "p99_ms": r["latency_ms"]["p99"],
                "success_rate": round(r["success_rate"], 4),
            }
        finally:
            stop_server(proc)
    return out


def run_cache_test(port: int, n: int = 100) -> dict:
    """Reference benchmark.py's cache-effectiveness A/B (its :180-220):
    n distinct inputs (miss phase), then the same n again (hit phase)."""
    import random

    rnd = random.Random(1234)
    inputs = [[rnd.uniform(0, 100) for _ in range(3)] for _ in range(n)]

    def phase(tag):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        lats = []
        for i, vec in enumerate(inputs):
            body = json.dumps({"request_id": f"cache_{tag}_{i}",
                               "input_data": vec})
            t0 = time.perf_counter()
            conn.request("POST", "/infer", body=body,
                         headers={"Content-Type": "application/json"})
            conn.getresponse().read()
            lats.append((time.perf_counter() - t0) * 1e3)
        conn.close()
        return statistics.fmean(lats)

    # Same request_id per input across phases so both route to one lane.
    miss_ms = phase("x")
    hit_ms = phase("x")
    return {
        "miss_avg_ms": round(miss_ms, 3),
        "hit_avg_ms": round(hit_ms, 3),
        "speedup": round(miss_ms / max(hit_ms, 1e-9), 2),
    }


def run_generate_bench(port: int, n_requests: int = 16, max_new: int = 32,
                       n_threads: int = 8) -> dict:
    """Autoregressive decode throughput: concurrent /generate requests,
    reports generated tokens/s (BASELINE config 5 workload)."""
    import random

    rnd = random.Random(7)
    prompts = [[rnd.randrange(1, 200) for _ in range(rnd.randrange(4, 24))]
               for _ in range(n_requests)]
    tokens_out = [0] * n_threads
    fails = [0] * n_threads

    def worker(tid):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        for i in range(tid, n_requests, n_threads):
            body = json.dumps({"request_id": f"gen_{i}",
                               "prompt_tokens": prompts[i],
                               "max_new_tokens": max_new})
            try:
                conn.request("POST", "/generate", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = json.loads(resp.read())
                if resp.status == 200:
                    tokens_out[tid] += len(data["tokens"])
                else:
                    fails[tid] += 1
            except (OSError, http.client.HTTPException):
                fails[tid] += 1
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        conn.close()

    # Warm the compiled prefill/decode executables before timing.
    warm = threading.Thread(target=worker, args=(0,))
    warm.start()
    warm.join()
    tokens_out[0] = 0

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(tid,))
               for tid in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total = sum(tokens_out)
    return {
        "tokens": total,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total / wall, 2) if wall > 0 else 0.0,
        "failed": sum(fails),
    }


def run_compute_bench(model: str = "resnet50", batch: int = 32,
                      iters: int = 30, dtype: str = "bfloat16") -> dict:
    """Device-compute benchmark with honest attribution (VERDICT r3 item 4).

    Two timed loops:
    - **device loop**: inputs pre-staged on device, outputs not read until
      the end (one forced scalar materialization — `block_until_ready` is
      unreliable through the axon tunnel). Per-iter time = executable +
      per-dispatch stream overhead; `mfu` is computed from THIS number and
      XLA's own cost analysis, so it reflects the device, not the host.
    - **e2e loop**: full `batch_predict` path with pre-generated distinct
      host inputs (RNG hoisted out of the loop) — staging + transfer +
      readback included; reported separately as `e2e_step_ms` /
      `host_overhead_ms`, never folded into MFU."""
    import numpy as np

    from tpu_engine.runtime.engine import InferenceEngine

    eng = InferenceEngine(model, dtype=dtype, batch_buckets=(batch,))
    wire = eng._wire_buckets[-1]  # full-width: the honest worst-case feed
    t0 = time.perf_counter()
    exe = eng._compiled(batch, wire=wire)
    compile_s = time.perf_counter() - t0

    flops_per_exec = None
    try:
        ca = exe.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops_per_exec = float(ca.get("flops", 0.0)) or None
    except Exception as exc:
        log(f"cost_analysis unavailable: {exc}")

    rng = np.random.default_rng(0)
    n_in = eng.input_size
    host_batches = [
        [rng.standard_normal(n_in).astype(np.float32) for _ in range(batch)]
        for _ in range(iters)
    ]

    # -- device loop: a few distinct pre-staged buffers, round-robin -------
    import jax

    staged = [eng._stage_wire(host_batches[k % iters][:batch], batch, wire)
              for k in range(min(4, iters))]
    y = exe(eng.params, staged[0])
    _ = np.asarray(jax.tree_util.tree_leaves(y)[0])[:1]  # hard sync (warm)
    t0 = time.perf_counter()
    for k in range(iters):
        y = exe(eng.params, staged[k % len(staged)])
    _ = np.asarray(jax.tree_util.tree_leaves(y)[0]).ravel()[:1]  # hard sync
    device_wall = time.perf_counter() - t0
    device_step_ms = device_wall / iters * 1e3

    # -- e2e loop: full miss path, distinct inputs, RNG pre-hoisted --------
    eng.batch_predict(host_batches[0])  # warm the e2e path
    t0 = time.perf_counter()
    for hb in host_batches:
        eng.batch_predict(hb)
    e2e_wall = time.perf_counter() - t0
    e2e_step_ms = e2e_wall / iters * 1e3

    kind, peak = chip_peak_flops()
    achieved = (flops_per_exec / (device_step_ms / 1e3)
                if flops_per_exec else None)
    return {
        "model": model,
        "batch": batch,
        "iters": iters,
        "device_step_ms": round(device_step_ms, 3),
        "e2e_step_ms": round(e2e_step_ms, 3),
        "host_overhead_ms": round(e2e_step_ms - device_step_ms, 3),
        "samples_per_s": round(batch / (e2e_step_ms / 1e3), 2),
        "device_samples_per_s": round(batch / (device_step_ms / 1e3), 2),
        "compile_s": round(compile_s, 2),
        "flops_per_batch": flops_per_exec,
        "achieved_tflops": round(achieved / 1e12, 2) if achieved else None,
        "device_kind": kind,
        "peak_tflops": round(peak / 1e12, 1) if peak else None,
        "mfu": round(achieved / peak, 4) if achieved and peak else None,
    }


def run_decode_compute(model: str = "gpt2", batch: int = 8,
                       max_new: int = 64, dtype: str = "bfloat16",
                       quantize: bool = False, fused: bool = False) -> dict:
    """On-chip decode throughput: tokens/s/chip through the KV-cache decode
    loop, with decode MFU ≈ tokens/s x 2 x params / peak (decode is
    HBM-bandwidth-bound; low MFU is expected and honest). `quantize` runs
    the same loop over int8 weight-only params (ops.quant) — decode streams
    every weight per step, so int8 halves its HBM bytes. `fused` runs the
    single-dispatch whole-loop mode (zero per-chunk host syncs — the
    honest device-capability number on a high-latency dispatch link)."""
    import numpy as np

    from tpu_engine.models.registry import create_model, _ensure_builtin_models_imported
    from tpu_engine.ops.nn import count_params
    from tpu_engine.runtime.generator import Generator

    _ensure_builtin_models_imported()
    spec = create_model(model)
    params = None
    if quantize:
        import jax

        from tpu_engine.ops.quant import quantize_params

        params = quantize_params(spec.init(jax.random.PRNGKey(0)))
    gen = Generator(spec, params=params, dtype=dtype, batch_buckets=(batch,))
    n_params = count_params(gen.params)

    rng = np.random.default_rng(1)
    prompts = [[int(t) for t in rng.integers(1, 1000, size=12)]
               for _ in range(batch)]
    t0 = time.perf_counter()
    # Compile with the measured max_new (fused caches one executable per
    # output-capacity bucket; a 4-token warm compile would miss it).
    gen.generate(prompts, max_new_tokens=max_new, fused=fused)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = gen.generate(prompts, max_new_tokens=max_new, temperature=0.0,
                       fused=fused)
    wall = time.perf_counter() - t0
    tokens = sum(len(o) for o in out)
    kind, peak = chip_peak_flops()
    tok_s = tokens / wall
    flops_s = tok_s * 2.0 * n_params  # matmul fwd ≈ 2*N FLOPs/token
    return {
        "model": model,
        "batch": batch,
        "max_new_tokens": max_new,
        "quantize": "int8" if quantize else None,
        "fused": fused,
        "tokens_per_s": round(tok_s, 2),
        "wall_s": round(wall, 3),
        "compile_s": round(compile_s, 2),
        "n_params": n_params,
        "device_kind": kind,
        "decode_mfu": round(flops_s / peak, 4) if peak else None,
    }


def run_decode_ab(model: str = "gpt2", n_requests: int = 24,
                  max_new: int = 32, mean_gap_ms: float = 40.0,
                  dtype: str = "bfloat16") -> dict:
    """Continuous vs batch-to-completion decode under Poisson arrivals
    (VERDICT r1 item 7): same model/params/workload, reports tokens/s and
    per-request latency for both schedulers."""
    import random

    import jax
    import numpy as np

    from tpu_engine.models.registry import create_model, _ensure_builtin_models_imported
    from tpu_engine.runtime.engine import InferenceEngine
    from tpu_engine.serving.worker import WorkerNode
    from tpu_engine.utils.config import WorkerConfig

    _ensure_builtin_models_imported()
    spec = create_model(model)
    params = spec.init(jax.random.PRNGKey(0))
    rnd = random.Random(42)
    prompts = [[rnd.randrange(1, 1000) for _ in range(rnd.randrange(4, 24))]
               for _ in range(n_requests)]
    gaps = [rnd.expovariate(1000.0 / mean_gap_ms) / 1000.0
            for _ in range(n_requests)]

    results = {}
    for sched in ("batch", "continuous"):
        cfg = WorkerConfig(model=model, node_id=f"ab-{sched}", dtype=dtype,
                           gen_scheduler=sched, batch_buckets=(1,))
        engine = InferenceEngine(spec, params=params, dtype=dtype,
                                 batch_buckets=(1,))
        w = WorkerNode(cfg, engine=engine)
        try:
            # Warm compiles outside the timed window.
            w.handle_generate({"request_id": "warm", "prompt_tokens": [1, 2, 3],
                               "max_new_tokens": 4})
            lats = [None] * n_requests
            threads = []

            def issue(i):
                t0 = time.perf_counter()
                w.handle_generate({"request_id": f"ab_{i}",
                                   "prompt_tokens": prompts[i],
                                   "max_new_tokens": max_new})
                lats[i] = (time.perf_counter() - t0) * 1e3

            t0 = time.perf_counter()
            for i in range(n_requests):
                time.sleep(gaps[i])
                th = threading.Thread(target=issue, args=(i,))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
            lat_sorted = sorted(lats)
            results[sched] = {
                "tokens_per_s": round(n_requests * max_new / wall, 2),
                "wall_s": round(wall, 3),
                "latency_p50_ms": round(lat_sorted[len(lats) // 2], 1),
                "latency_p95_ms": round(lat_sorted[int(0.95 * len(lats))
                                                   - 1], 1),
            }
        finally:
            w.stop()
    cont, bat = results["continuous"], results["batch"]
    results["continuous_speedup"] = round(
        cont["tokens_per_s"] / max(bat["tokens_per_s"], 1e-9), 3)
    return results


def run_spec_ab(model: str = "gpt2", batch: int = 8, max_new: int = 64,
                k: int = 4, dtype: str = "bfloat16") -> dict:
    """Speculative vs plain batch decode: same target params, greedy, batch
    workload. Two drafts bracket the win envelope — the target itself
    (acceptance 1: the machinery's best case) and a random-init distilgpt2
    (acceptance ~0: pure overhead floor). Real drafts (imported distilgpt2
    weights vs gpt2) land between; with the whole round loop compiled
    on-device, the speculative path also removes every per-chunk host sync
    the plain scheduler pays (runtime/speculative.py)."""
    import jax
    import numpy as np

    from tpu_engine.models.registry import (create_model,
                                            _ensure_builtin_models_imported)
    from tpu_engine.runtime.generator import Generator
    from tpu_engine.runtime.speculative import SpeculativeGenerator

    _ensure_builtin_models_imported()
    spec = create_model(model)
    params = spec.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(7)
    prompts = [[int(t) for t in rng.integers(1, 1000, size=12)]
               for _ in range(batch)]

    def timed(gen):
        t0 = time.perf_counter()
        gen.generate(prompts, max_new_tokens=max_new)     # compile + warm
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = gen.generate(prompts, max_new_tokens=max_new)
        wall = time.perf_counter() - t0
        toks = sum(len(o) for o in out)
        return out, {"tokens_per_s": round(toks / wall, 2),
                     "wall_s": round(wall, 3),
                     "compile_s": round(compile_s, 2)}

    def prefix_match(got, want):
        # Strict equality is too brittle under bf16: the windowed verify
        # and the sequential decode are different reductions, and a
        # near-tied argmax can legitimately flip (after which the streams
        # diverge). Report the mean fraction of the stream matching up to
        # the first divergence instead (1.0 under f32, tested).
        fracs = []
        for g, w in zip(got, want):
            n = min(len(g), len(w)) or 1
            i = 0
            while i < n and g[i] == w[i]:
                i += 1
            fracs.append(i / n)
        return round(sum(fracs) / len(fracs), 3)

    plain = Generator(spec, params=params, dtype=dtype,
                      batch_buckets=(batch,))
    want, plain_r = timed(plain)

    results = {"model": model, "batch": batch, "max_new_tokens": max_new,
               "k": k, "plain_batch": plain_r}
    from tpu_engine.ops.quant import quantize_params

    # int8_self_draft is the deployable no-second-checkpoint draft: the
    # TARGET's weights quantized int8 draft the bf16 target. The draft
    # step reads half the weight HBM bytes (decode is weight-bound on
    # chip) yet almost never flips the argmax, so acceptance stays near
    # k+1 — a real speedup, unlike the same-cost self_draft upper bound
    # or the random floor (VERDICT r4 weak item 3).
    drafts = [("self_draft", spec, params),
              ("int8_self_draft", create_model(model),
               quantize_params(params)),
              ("random_distilgpt2", create_model("distilgpt2"), None)
              if model == "gpt2" else
              ("random_same_arch", create_model(model), None)]
    for name, dspec, dparams in drafts:
        sg = SpeculativeGenerator(spec, dspec, params=params,
                                  draft_params=dparams, k=k, dtype=dtype,
                                  batch_buckets=(batch,))
        got, r = timed(sg)
        r["greedy_prefix_match_frac"] = prefix_match(got, want)
        r["mean_tokens_per_round"] = sg.last_stats.get(
            "mean_tokens_per_round")
        r["speedup_vs_plain"] = round(
            r["tokens_per_s"] / max(plain_r["tokens_per_s"], 1e-9), 3)
        results[name] = r
    return results


def run_prefill_mfu(model: str = "gpt2", batch: int = 8, seq: int = 1024,
                    iters: int = 10, dtype: str = "bfloat16") -> dict:
    """Transformer-prefill MFU — the matmul-dense flagship (VERDICT r4
    item 2's alternative): prefill is back-to-back (B*S, d) x (d, *)
    matmuls, the shape the MXU was built for, where a CNN's small-channel
    early convs are not. Pure device loop (inputs pre-staged, one hard
    sync at the end), FLOPs from XLA's own cost analysis."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.models.transformer import init_caches, transformer_prefill

    _ensure_builtin_models_imported()
    spec = create_model(model, max_seq=seq)
    cfg = spec.config
    dt = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[dtype]
    params = spec.init(jax.random.PRNGKey(0))

    def prefill(p, tokens, caches):
        return transformer_prefill(p, tokens, caches, cfg, dtype=dt)

    tokens = jnp.asarray(np.random.default_rng(0).integers(
        1, cfg.vocab, (batch, seq)), jnp.int32)
    caches = init_caches(cfg, batch, seq, dt)
    t0 = time.perf_counter()
    exe = jax.jit(prefill).lower(params, tokens, caches).compile()
    compile_s = time.perf_counter() - t0
    flops = None
    try:
        ca = exe.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops = float(ca.get("flops", 0.0)) or None
    except Exception as exc:
        log(f"cost_analysis unavailable: {exc}")

    logits, _ = exe(params, tokens, caches)
    _ = np.asarray(logits).ravel()[:1]  # hard sync (warm)
    t0 = time.perf_counter()
    for _k in range(iters):
        logits, _ = exe(params, tokens, caches)
    _ = np.asarray(logits).ravel()[:1]
    step_ms = (time.perf_counter() - t0) / iters * 1e3

    kind, peak = chip_peak_flops()
    achieved = flops / (step_ms / 1e3) if flops else None
    return {
        "model": model, "batch": batch, "seq": seq, "dtype": dtype,
        "device_kind": kind,
        "compile_s": round(compile_s, 2),
        "device_step_ms": round(step_ms, 3),
        "prefill_tokens_per_s": round(batch * seq / (step_ms / 1e3), 1),
        "flops_per_step": flops,
        "achieved_tflops": round(achieved / 1e12, 2) if achieved else None,
        "mfu": round(achieved / peak, 4) if achieved and peak else None,
    }


def run_longcontext_prefill(model: str = "gpt2",
                            seqs: Sequence[int] = (4096, 8192),
                            batch: int = 1, iters: int = 5,
                            xla_arm_max_seq: int = 4096) -> dict:
    """Long-context serving proof (VERDICT r4 item 7): gpt2 wired through
    the GENERATOR's flash prefill at S4k-8k — the sequences whose S^2
    score temps kill the unfused path. Measures prefill tok/s through the
    real serving entry (Generator.generate, prompt-bucketed, two decode
    steps so the path is the production one, prefill dominating). The XLA
    arm (TPU_ENGINE_FLASH=0) runs only to `xla_arm_max_seq` — at S8192 it
    cannot compile on a 16 GB chip (44 GB of S^2 temps, PERF.md)."""
    import os

    import numpy as np

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.generator import Generator

    _ensure_builtin_models_imported()
    max_seq = max(seqs)
    rng = np.random.default_rng(3)
    out: dict = {"model": model, "batch": batch}
    prior_flash = os.environ.get("TPU_ENGINE_FLASH")  # restore, don't pop:
    # clobbering a caller-forced mode would silently change attention
    # selection for every stage that runs after this one.
    for attn, label in (("auto", "flash"), ("0", "xla")):
        os.environ["TPU_ENGINE_FLASH"] = attn
        try:
            # Fresh generator per arm: the attention choice is baked at
            # trace time.
            spec = create_model(model, max_seq=max_seq)
            gen = Generator(spec, dtype="bfloat16", batch_buckets=(batch,),
                            prompt_buckets=tuple(seqs), max_seq=max_seq)
            for s in seqs:
                if label == "xla" and s > xla_arm_max_seq:
                    out[f"xla_S{s}"] = "skipped: S^2 temps exceed HBM"
                    continue
                plen = s - 2  # prompt bucket s, two decode steps inside it
                prompts = [[int(t) for t in rng.integers(1, 1000, plen)]
                           for _ in range(batch)]
                t0 = time.perf_counter()
                gen.generate(prompts, max_new_tokens=2)  # compile + warm
                compile_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                for _k in range(iters):
                    gen.generate(prompts, max_new_tokens=2)
                wall = (time.perf_counter() - t0) / iters
                out[f"{label}_S{s}"] = {
                    "prefill_tokens_per_s": round(batch * plen / wall, 1),
                    "wall_s": round(wall, 3),
                    "compile_s": round(compile_s, 2),
                }
        finally:
            if prior_flash is None:
                os.environ.pop("TPU_ENGINE_FLASH", None)
            else:
                os.environ["TPU_ENGINE_FLASH"] = prior_flash
    return out


def run_mixed_shape_bench(port: int, n_requests: int = 2000,
                          n_threads: int = 16) -> dict:
    """Mixed-shape load (BASELINE config 4): yolov8n requests cycling three
    resolutions with distinct payloads, stressing the (shape, batch)
    executable cache under concurrent traffic."""
    import random

    rnd = random.Random(9)
    shapes = [(320, 320, 3), (480, 480, 3), (640, 640, 3)]
    lat = [[] for _ in range(n_threads)]
    fails = [0] * n_threads

    def worker(tid):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        for i in range(tid, n_requests, n_threads):
            shape = shapes[i % len(shapes)]
            # Tiny distinct payload; engine zero-pads to the true shape —
            # wire cost stays client-bound, device cost is the real shape.
            body = json.dumps({
                "request_id": f"mix_{i}",
                "input_data": [rnd.random() for _ in range(16)],
                "shape": list(shape),
            })
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/infer", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status == 200:
                    lat[tid].append((time.perf_counter() - t0) * 1e3)
                else:
                    fails[tid] += 1
            except (OSError, http.client.HTTPException):
                fails[tid] += 1
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.close()

    # Warm every (shape, batch) bucket before timing.
    warm = threading.Thread(target=worker, args=(0,))
    warm.start()
    warm.join()
    lat[0] = []

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lats = sorted(x for chunk in lat for x in chunk)
    return {
        "requests": n_requests,
        "shapes": [list(s) for s in shapes],
        "throughput_req_s": round(len(lats) / wall, 2),
        "p50_ms": round(lats[len(lats) // 2], 2) if lats else None,
        "p99_ms": round(lats[int(0.99 * len(lats)) - 1], 2) if lats else None,
        "failed": sum(fails),
    }


def run_paged_ab(model: str = "gpt2-small-test", n_requests: int = 16,
                 max_new: int = 96, shared_max_new: int = 16,
                 prompt_len: int = 8, shared_prefix_len: int = 64,
                 mean_gap_ms: float = 15.0, dtype: str = "float32",
                 block_size: int = 16, dense_slots: int = 2,
                 max_seq: int = 512) -> dict:
    """Dense vs paged KV cache at EQUAL KV memory budget (the tentpole
    A/B). Two arms:

    - **capacity**: a burst of short prompts against (a) the dense
      scheduler (`dense_slots` rows of max_seq each) and (b) the paged
      scheduler given exactly the same KV bytes as a block pool
      (`dense_slots * ceil(max_seq/bs)` blocks), with its slot count
      sized to what those blocks can hold concurrently at this
      workload's row footprint. Reports the peak concurrently-admitted
      rows each sustained — paged rows reserve blocks for the tokens
      they actually hold, so the same HBM admits several times more
      short rows.
    - **shared-prefix**: Poisson arrivals of prompts sharing one
      system-prompt prefix, paged with radix sharing on vs off. Reports
      prefill-token savings (prefix_hit_tokens vs prefilled_tokens) and
      tokens/s.

    Runs on the CPU mesh (tiny default model, max_seq overridden on the
    spec: the capacity and sharing ratios are layout/workload
    properties, not model-size properties); the on-chip campaign re-runs
    it against gpt2 on the device."""
    import random

    import jax

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.scheduler import ContinuousGenerator

    _ensure_builtin_models_imported()
    spec = create_model(model, max_seq=max_seq)
    params = spec.init(jax.random.PRNGKey(0))
    step_chunk = 8
    width = -(-max_seq // block_size)
    kv_blocks = dense_slots * width + 1  # == dense KV bytes (+ null block)
    # Worst-case blocks one capacity-arm row pins (prompt + generation +
    # one chunk of headroom): the pool admits this many rows at once.
    per_row_blocks = -(-(prompt_len + max_new + step_chunk) // block_size)
    paged_slots = max(1, (kv_blocks - 1) // per_row_blocks)
    rnd = random.Random(42)

    def run_burst(gen, prompts, new_tokens, gaps=None):
        peak = [0]
        stop_flag = threading.Event()

        def sampler():
            while not stop_flag.is_set():
                peak[0] = max(peak[0], gen.stats()["active"])
                time.sleep(0.002)

        th = threading.Thread(target=sampler, daemon=True)
        th.start()
        t0 = time.perf_counter()
        futs = []
        for i, p in enumerate(prompts):
            if gaps:
                time.sleep(gaps[i])
            futs.append(gen.submit(p, max_new_tokens=new_tokens))
        outs = [f.result(600) for f in futs]
        wall = time.perf_counter() - t0
        stop_flag.set()
        th.join(timeout=1)
        toks = sum(len(o) for o in outs)
        short = sum(1 for o in outs if len(o) < new_tokens)
        return {"requests": len(prompts), "wall_s": round(wall, 3),
                "tokens": toks, "truncated_rows": short,
                "tokens_per_s": round(toks / wall, 2) if wall else 0.0,
                "peak_concurrent_rows": peak[0]}

    results = {"model": model, "max_seq": max_seq,
               "block_size": block_size, "dense_slots": dense_slots,
               "paged_slots_equal_budget": paged_slots,
               "kv_blocks_equal_budget": kv_blocks}
    # A few distinct prompts cycled (the reference benchmark's own
    # workload shape): admission cost is then prefix-cache/radix-cheap on
    # both arms, so the burst measures RESIDENCY capacity, not the CPU
    # mesh's serial prefill throughput.
    distinct = [[rnd.randrange(1, 200) for _ in range(prompt_len)]
                for _ in range(4)]
    prompts = [distinct[i % len(distinct)] for i in range(n_requests)]

    dense = ContinuousGenerator(spec, params=params, dtype=dtype,
                                n_slots=dense_slots, step_chunk=step_chunk,
                                max_seq=max_seq)
    try:
        dense.generate(distinct, max_new_tokens=2)  # warm compiles+cache
        results["dense"] = run_burst(dense, prompts, max_new)
    finally:
        dense.stop()
    record_partial("paged_ab_dense", results["dense"])
    paged = ContinuousGenerator(spec, params=params, dtype=dtype,
                                n_slots=paged_slots, step_chunk=step_chunk,
                                max_seq=max_seq, kv_block_size=block_size,
                                kv_blocks=kv_blocks)
    try:
        paged.generate(distinct, max_new_tokens=2)
        results["paged"] = run_burst(paged, prompts, max_new)
        results["paged"]["kv_pool"] = {
            k: paged.stats()["kv_pool"][k]
            for k in ("blocks_total", "blocks_free", "evictions")}
    finally:
        paged.stop()
    results["capacity_gain"] = round(
        results["paged"]["peak_concurrent_rows"]
        / max(1, results["dense"]["peak_concurrent_rows"]), 2)
    record_partial("paged_ab_capacity", {
        k: results[k] for k in ("dense", "paged", "capacity_gain")})

    # Shared-prefix Poisson arm: radix sharing on vs off, same arrivals.
    shared = [rnd.randrange(1, 200) for _ in range(shared_prefix_len)]
    sp = [shared + [rnd.randrange(1, 200) for _ in range(6)]
          for _ in range(n_requests)]
    gaps = [rnd.expovariate(1000.0 / mean_gap_ms) / 1000.0
            for _ in range(n_requests)]
    for label, sharing in (("paged_shared_prefix", True),
                           ("paged_no_sharing", False)):
        g = ContinuousGenerator(spec, params=params, dtype=dtype,
                                n_slots=paged_slots, step_chunk=step_chunk,
                                max_seq=max_seq, kv_block_size=block_size,
                                kv_blocks=kv_blocks,
                                prefix_sharing=sharing)
        try:
            # Warm the full prefill path AND (sharing arm) the resumed
            # mid-prompt window widths, so the timed burst measures the
            # steady state, not one-time XLA compiles.
            g.generate([sp[0]], max_new_tokens=2)
            g.generate([shared + [1, 2, 3]], max_new_tokens=2)
            r = run_burst(g, sp, shared_max_new, gaps=gaps)
            pool = g.stats()["kv_pool"]
            r["kv_pool"] = {k: pool[k] for k in
                            ("prefix_hit_tokens", "prefilled_tokens",
                             "prefix_savings_frac", "blocks_shared",
                             "radix_nodes", "evictions")}
            results[label] = r
        finally:
            g.stop()
        record_partial(label, results[label])
    results["prefill_token_savings_frac"] = \
        results["paged_shared_prefix"]["kv_pool"]["prefix_savings_frac"]
    return results


def run_quant_ab(model: str = "gpt2-small-test", n_requests: int = 24,
                 max_new: int = 96, shared_prefix_len: int = 32,
                 prompt_tail: int = 6,
                 dtype: str = "bfloat16", block_size: int = 16,
                 bf16_rows: int = 3, max_seq: int = 256,
                 model_kwargs: Optional[dict] = None) -> dict:
    """bf16 vs int8 KV block pool at EQUAL KV byte budget (the
    --kv-quantize tentpole A/B, in the paged-ab shape). Three arms, all
    paged with radix prefix sharing ON and a shared-prefix burst so the
    prefix-skip machinery stays engaged:

    - **bf16** (defaults-off): today's pool, sized to ``bf16_rows`` rows
      of max_seq. Run twice — the repeat must be byte-identical (the
      defaults-off arm IS pre-quantization behavior) and its /stats
      kv_pool must carry no `quantized` key.
    - **int8**: the same KV bytes as a quantized pool — about 2x the
      blocks (payload halves; the per-slot f32 scales cost 4/(D+4) of
      the win, so ~1.88x at d_head 64) — with its slot count sized to
      what those blocks hold at this workload's row footprint. Run
      twice — quantized greedy streams must be deterministic across
      repeats. The headline is peak concurrently-admitted rows:
      capacity_gain = int8 peak / bf16 peak, bar >= 1.8x.

    The default model override (d_model 128, n_heads 2) gives the tiny
    test config a SERVING-SHAPED d_head of 64 — at the test model's
    native d_head 16 the scale overhead would mask the byte win that
    real models (d_head 64-128) actually see; the on-chip campaign runs
    the same A/B against gpt2 (d_head 64) on the device."""
    import random

    import jax

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.scheduler import ContinuousGenerator

    _ensure_builtin_models_imported()
    if model_kwargs is None:
        model_kwargs = ({"d_model": 128, "n_heads": 2}
                        if model == "gpt2-small-test" else {})
    spec = create_model(model, max_seq=max_seq, **model_kwargs)
    params = spec.init(jax.random.PRNGKey(0))
    cfg = spec.config
    # Small decode chunks: rows live many chunks, so the burst's
    # steady-state concurrency is bound by SLOT capacity (the thing the
    # A/B measures), not by the serial admission rate of the host mesh.
    step_chunk = 2
    width = -(-max_seq // block_size)
    bf16_blocks = bf16_rows * width + 1
    # Equal BYTE budget, not equal block count: the quantized pool gets
    # however many int8+scale blocks fit in the bf16 arm's KV bytes —
    # sized by the POOL'S OWN layout formulas, never a re-derivation.
    import jax.numpy as jnp

    from tpu_engine.runtime.kv_blocks import (dense_block_bytes,
                                              quant_block_bytes)

    dense_bpb = dense_block_bytes(
        cfg, block_size,
        {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[dtype])
    quant_bpb = quant_block_bytes(cfg, block_size)
    budget_bytes = (bf16_blocks - 1) * dense_bpb
    quant_blocks = budget_bytes // quant_bpb + 1
    prompt_len = shared_prefix_len + prompt_tail
    per_row_blocks = -(-(prompt_len + max_new + step_chunk) // block_size)
    bf16_slots = max(1, (bf16_blocks - 1) // per_row_blocks)
    quant_slots = max(1, (quant_blocks - 1) // per_row_blocks)
    rnd = random.Random(7)
    shared = [rnd.randrange(1, 200) for _ in range(shared_prefix_len)]
    prompts = [shared + [rnd.randrange(1, 200) for _ in range(prompt_tail)]
               for _ in range(n_requests)]

    def run_burst(gen, new_tokens):
        peak = [0]
        stop_flag = threading.Event()

        def sampler():
            while not stop_flag.is_set():
                peak[0] = max(peak[0], gen.stats()["active"])
                time.sleep(0.002)

        th = threading.Thread(target=sampler, daemon=True)
        th.start()
        t0 = time.perf_counter()
        futs = [gen.submit(p, max_new_tokens=new_tokens) for p in prompts]
        outs = [f.result(600) for f in futs]
        wall = time.perf_counter() - t0
        stop_flag.set()
        th.join(timeout=1)
        toks = sum(len(o) for o in outs)
        return outs, {"requests": len(prompts), "wall_s": round(wall, 3),
                      "tokens": toks,
                      "tokens_per_s": round(toks / wall, 2) if wall else 0.0,
                      "peak_concurrent_rows": peak[0]}

    def run_arm(quantize: str, n_slots: int, kv_blocks: int):
        gen = ContinuousGenerator(
            spec, params=params, dtype=dtype, n_slots=n_slots,
            step_chunk=step_chunk, max_seq=max_seq,
            kv_block_size=block_size, kv_blocks=kv_blocks,
            kv_quantize=quantize)
        try:
            # Warm compiles + the resumed mid-prompt window widths so the
            # timed bursts measure steady state, not one-time XLA work.
            gen.generate([prompts[0]], max_new_tokens=2)
            gen.generate([shared + [1, 2, 3]], max_new_tokens=2)
            streams1, r1 = run_burst(gen, max_new)
            streams2, r2 = run_burst(gen, max_new)
            pool = gen.stats()["kv_pool"]
            r1["repeat_identical"] = streams1 == streams2
            r1["kv_pool"] = {k: pool[k] for k in
                             ("blocks_total", "block_size",
                              "prefix_savings_frac", "radix_hits")
                             if k in pool}
            for k in ("quantized", "bytes_per_block",
                      "dense_bytes_per_block", "capacity_multiplier"):
                if k in pool:
                    r1["kv_pool"][k] = pool[k]
            r1["stats_has_quantized_key"] = "quantized" in pool
            r1["peak_concurrent_rows"] = max(r1["peak_concurrent_rows"],
                                             r2["peak_concurrent_rows"])
        finally:
            gen.stop()
        return streams1, r1

    results = {"model": model, "model_kwargs": model_kwargs,
               "max_seq": max_seq, "block_size": block_size,
               "dtype": dtype, "d_head": cfg.d_head,
               "kv_byte_budget": int(budget_bytes),
               "bf16": {"kv_blocks": bf16_blocks, "n_slots": bf16_slots},
               "int8": {"kv_blocks": int(quant_blocks),
                        "n_slots": quant_slots}}
    bf16_streams, bf16_r = run_arm("", bf16_slots, bf16_blocks)
    results["bf16"].update(bf16_r)
    record_partial("quant_ab_bf16", results["bf16"])
    int8_streams, int8_r = run_arm("int8", quant_slots, int(quant_blocks))
    results["int8"].update(int8_r)
    record_partial("quant_ab_int8", results["int8"])

    results["capacity_gain"] = round(
        results["int8"]["peak_concurrent_rows"]
        / max(1, results["bf16"]["peak_concurrent_rows"]), 2)
    agree = [a == b for a, b in zip(int8_streams, bf16_streams)]
    tok_agree = [sum(x == y for x, y in zip(a, b)) / max(1, len(a))
                 for a, b in zip(int8_streams, bf16_streams)]
    results["streams_identical_to_bf16_frac"] = round(
        sum(agree) / len(agree), 3)
    results["token_agreement_frac"] = round(
        sum(tok_agree) / len(tok_agree), 4)
    results["checks_passed"] = bool(
        results["capacity_gain"] >= 1.8
        and results["int8"]["repeat_identical"]          # deterministic
        and results["bf16"]["repeat_identical"]          # defaults-off
        and not results["bf16"]["stats_has_quantized_key"]
        and results["int8"]["stats_has_quantized_key"]
        and results["bf16"]["kv_pool"]["prefix_savings_frac"] > 0
        and results["int8"]["kv_pool"]["prefix_savings_frac"] > 0)
    return results


def run_recurrent_ab(att_model: str = "gpt2-small-test",
                     ssd_model: str = "ssd-small-test",
                     n_requests: int = 12, max_new: int = 16,
                     seq_sweep=(46, 110, 238), att_rows_budget: int = 3,
                     block_size: int = 16, max_seq: int = 256,
                     n_slots: int = 16, mixed_budget: int = 32,
                     quick: bool = False) -> dict:
    """Attention (kv_paged) vs SSD (state_slab) at EQUAL HBM budget —
    the O(1)-state tentpole A/B. One byte budget, sized to
    ``att_rows_budget`` full-length attention rows, provisions BOTH
    arms' pools: the attention arm gets that many KV blocks, the SSD
    arm however many fixed-size state rows fit in the same bytes. A
    saturating greedy burst of ``n_requests`` streams runs at each
    SEQUENCE LENGTH in ``seq_sweep`` (prompt lengths; +max_new decode
    tokens each) and the headline is PEAK CONCURRENT ROWS vs length:

    - attention rows allocate their prompt bucket's blocks AT
      admission, so the pool binds exactly there: peak rows FALL as
      sequences lengthen (excess admissions defer, the PR 3 parking);
    - SSD rows need exactly ONE state row forever, so peak rows are
      CONSTANT in sequence length — "KV capacity" became "state
      capacity", and it does not depreciate with context.

    Both arms run MIXED stepping so a row occupies its slot from
    admission through prefill and decode (concurrency measures pool
    capacity, not the host mesh's serial admission rate), and the
    sweep lengths are chosen so prompt+decode never outgrows the
    admission-time bucket — the pool binds at ADMISSION, never by
    mid-stream starvation (starved early completions would poison the
    determinism check). Every burst runs twice (streams must be
    byte-identical run to run, both arms) and every pool must account
    for every block/row after each burst (zero slab leaks — rows_free
    == rows_total on the SSD arm, blocks free+radix-held == total on
    the attention arm). CPU mesh; the artifact carries the device
    stamp like every in-process A/B."""
    import random

    import jax
    import jax.numpy as jnp

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.kv_blocks import dense_block_bytes
    from tpu_engine.runtime.scheduler import ContinuousGenerator

    _ensure_builtin_models_imported()
    if quick:
        seq_sweep = (seq_sweep[0], seq_sweep[-1])
        n_requests = min(n_requests, 8)
    att_spec = create_model(att_model, max_seq=max_seq)
    ssd_spec = create_model(ssd_model, max_seq=max_seq)
    att_params = att_spec.init(jax.random.PRNGKey(0))
    ssd_params = ssd_spec.init(jax.random.PRNGKey(0))
    # Equal BYTE budget from the pools' OWN layout formulas (never a
    # re-derivation): att_rows_budget full-length attention rows.
    width = -(-max_seq // block_size)
    dense_bpb = dense_block_bytes(att_spec.config, block_size,
                                  jnp.bfloat16)
    budget_bytes = att_rows_budget * width * dense_bpb
    att_blocks = budget_bytes // dense_bpb + 1  # +1: the null block
    # The SSD row cost comes from the pool's own layout formula.
    from tpu_engine.models.ssd import ssd_state_dim
    ssd_row_bytes = ssd_spec.config.n_layers \
        * ssd_state_dim(ssd_spec.config) * 4
    ssd_rows = budget_bytes // ssd_row_bytes + 1  # +1: the null row
    rnd = random.Random(11)

    def run_burst(gen, prompts):
        peak = [0]
        stop_flag = threading.Event()

        def sampler():
            while not stop_flag.is_set():
                peak[0] = max(peak[0], gen.stats()["active"])
                time.sleep(0.002)

        th = threading.Thread(target=sampler, daemon=True)
        th.start()
        t0 = time.perf_counter()
        futs = [gen.submit(p, max_new_tokens=max_new) for p in prompts]
        outs = [f.result(600) for f in futs]
        wall = time.perf_counter() - t0
        stop_flag.set()
        th.join(timeout=1)
        toks = sum(len(o) for o in outs)
        return outs, {"wall_s": round(wall, 3), "tokens": toks,
                      "tokens_per_s": round(toks / wall, 2) if wall
                      else 0.0,
                      "peak_concurrent_rows": peak[0]}

    def sweep_arm(arm: str):
        per_len = {}
        deterministic = True
        leaks_clean = True
        complete = True
        for plen in seq_sweep:
            prompts = [[rnd.randrange(1, 200) for _ in range(plen)]
                       for _ in range(n_requests)]
            if arm == "ssd":
                gen = ContinuousGenerator(
                    ssd_spec, params=ssd_params, dtype="float32",
                    n_slots=n_slots, max_seq=max_seq,
                    prefill_chunk=block_size, mixed_step=True,
                    mixed_token_budget=mixed_budget,
                    state_rows=int(ssd_rows))
            else:
                gen = ContinuousGenerator(
                    att_spec, params=att_params, dtype="bfloat16",
                    n_slots=n_slots, max_seq=max_seq,
                    prefill_chunk=block_size, mixed_step=True,
                    mixed_token_budget=mixed_budget,
                    kv_block_size=block_size, kv_blocks=int(att_blocks),
                    prefix_sharing=False)
            try:
                gen.generate([prompts[0][:8]], max_new_tokens=2)  # warm
                s1, r1 = run_burst(gen, prompts)
                s2, r2 = run_burst(gen, prompts)
                deterministic &= s1 == s2
                # Full-length streams only: a starved early completion
                # would mean the pool bound mid-stream, not at
                # admission — the A/B's sizing contract.
                complete &= all(len(o) == max_new for o in s1 + s2)
                r1["peak_concurrent_rows"] = max(
                    r1["peak_concurrent_rows"],
                    r2["peak_concurrent_rows"])
                st = gen.stats()
                if arm == "ssd":
                    pool = st["state_pool"]
                    r1["pool"] = {k: pool[k] for k in
                                  ("rows_total", "rows_free",
                                   "bytes_per_row")}
                    leaks_clean &= (pool["rows_free"]
                                    == pool["rows_total"])
                else:
                    pool = st["kv_pool"]
                    r1["pool"] = {k: pool[k] for k in
                                  ("blocks_total", "blocks_free",
                                   "radix_nodes")}
                    leaks_clean &= (pool["blocks_free"]
                                    + pool["radix_nodes"]
                                    >= pool["blocks_total"])
            finally:
                gen.stop()
            per_len[plen] = r1
        return {"per_seq_len": per_len,
                "streams_deterministic": deterministic,
                "streams_complete": complete,
                "pools_leak_free": leaks_clean}

    ssd_res = sweep_arm("ssd")
    att_res = sweep_arm("att")
    ssd_peaks = [ssd_res["per_seq_len"][s]["peak_concurrent_rows"]
                 for s in seq_sweep]
    att_peaks = [att_res["per_seq_len"][s]["peak_concurrent_rows"]
                 for s in seq_sweep]
    longest = seq_sweep[-1]
    results = {
        "att_model": att_model, "ssd_model": ssd_model,
        "max_seq": max_seq, "block_size": block_size,
        "n_slots": n_slots, "n_requests": n_requests,
        "hbm_byte_budget": int(budget_bytes),
        "att": {"kv_blocks": int(att_blocks),
                "bytes_per_block": int(dense_bpb), **att_res},
        "ssd": {"state_rows": int(ssd_rows),
                "bytes_per_row": int(ssd_row_bytes), **ssd_res},
        "seq_sweep": list(seq_sweep),
        "ssd_peak_rows": ssd_peaks,
        "att_peak_rows": att_peaks,
        # The capacity story at the longest length: constant-state rows
        # vs linearly-depreciating KV rows on the same HBM.
        "capacity_gain_at_longest": round(
            ssd_peaks[-1] / max(1, att_peaks[-1]), 2),
    }
    results["checks_passed"] = bool(
        # SSD peak concurrent rows constant in sequence length...
        len(set(ssd_peaks)) == 1
        # ...while the attention arm's fall as streams lengthen...
        and att_peaks[-1] < att_peaks[0]
        # ...and the SSD arm holds more rows at the longest length.
        and ssd_peaks[-1] > att_peaks[-1]
        and ssd_res["streams_deterministic"]
        and att_res["streams_deterministic"]
        and ssd_res["streams_complete"]
        and att_res["streams_complete"]
        and ssd_res["pools_leak_free"]
        and att_res["pools_leak_free"]
        # The sweep actually saturated the SSD arm (peak == the burst).
        and ssd_peaks[-1] == min(n_requests, n_slots))
    return results


def run_tp_ab(model: str = "gpt2-small-test", tp: int = 4,
              blocks_per_device: int = 12, n_requests: int = 24,
              short_prompt_len: int = 18, long_prompt_len: int = 230,
              max_new: int = 12, block_size: int = 16,
              max_seq: int = 256, single_max_seq: int = 64,
              n_slots: int = 16, quick: bool = False) -> dict:
    """Tensor-parallel serving A/B at EQUAL PER-DEVICE HBM budget (the
    TP tentpole): every arm gets ``blocks_per_device`` KV blocks per
    chip — the TP arm's pool is tp x that many blocks sharded over its
    mesh, the single-device arm exactly that many on its one chip.

    Two facets, both provable on the CPU mesh:

    - MODEL-SIZE UNLOCK: at this per-device budget a single-device lane
      cannot hold even ONE ``max_seq`` KV row — the engine REFUSES
      OUTRIGHT at construction (the pinned "cannot hold even one
      max_seq row" ValueError; recorded verbatim), and its weights sit
      whole on the chip. The TP arm serves the exact same model +
      max_seq (params sharded by the registry rule, pool tp x deeper)
      and completes a ``long_prompt_len``-token stream — the "models
      too big for one chip" unlock, in pool terms. Per-device param
      bytes are measured from the PLACED tree's real shard shapes.
    - CAPACITY: a saturating burst of short greedy streams on the TP
      arm vs a single-device arm that — to exist at all at this budget
      — must shrink its context window to ``single_max_seq``. Peak
      concurrent rows (sampled from stats) scale with the pooled
      blocks.

    Every burst runs twice (streams byte-identical run to run), the TP
    arm's short streams must equal the single arm's BYTE-FOR-BYTE
    (cross-geometry stream identity — the same fold_in(seed, position)
    + paged-layout argument as every other identity in this engine),
    mixed ticks == dispatches on the sharded arm (one SPMD dispatch per
    tick), and every pool accounts for every block after each burst.
    Short prompts are sized so prompt + max_new + the decode horizon
    fits the admission bucket — the pools bind at ADMISSION (deferred
    admissions, deterministic), never by mid-stream starvation (whose
    early completions are timing-dependent and would poison the
    determinism check). Streams must run FULL length on both arms.
    CPU mesh; on-chip rerun pending like r06-r15."""
    import random

    import jax
    import numpy as _np

    from tpu_engine.models.registry import (
        _ensure_builtin_models_imported, create_model)
    from tpu_engine.runtime.kv_blocks import dense_block_bytes
    from tpu_engine.runtime.scheduler import ContinuousGenerator

    _ensure_builtin_models_imported()
    if quick:
        n_requests = min(n_requests, 12)
        tp = min(tp, 2)
    spec = create_model(model, max_seq=max_seq)
    params = spec.init(jax.random.PRNGKey(0))
    import jax.numpy as _jnp

    bpb = dense_block_bytes(spec.config, block_size, _jnp.float32)
    rnd = random.Random(17)
    short_prompts = [[rnd.randrange(1, 200)
                      for _ in range(short_prompt_len)]
                     for _ in range(n_requests)]
    long_prompt = [rnd.randrange(1, 200) for _ in range(long_prompt_len)]

    def param_bytes_per_device(tree) -> int:
        total = 0
        for leaf in jax.tree.leaves(tree):
            sh = getattr(leaf, "sharding", None)
            if sh is None:
                total += leaf.size * leaf.dtype.itemsize
                continue
            shard = sh.shard_shape(leaf.shape)
            total += int(_np.prod(shard)) * leaf.dtype.itemsize
        return int(total)

    def run_burst(gen, prompts):
        peak = [0]
        stop_flag = threading.Event()

        def sampler():
            while not stop_flag.is_set():
                peak[0] = max(peak[0], gen.stats()["active"])
                time.sleep(0.002)

        th = threading.Thread(target=sampler, daemon=True)
        th.start()
        t0 = time.perf_counter()
        futs = [gen.submit(p, max_new_tokens=max_new) for p in prompts]
        outs = [f.result(600) for f in futs]
        wall = time.perf_counter() - t0
        stop_flag.set()
        th.join(timeout=1)
        toks = sum(len(o) for o in outs)
        return outs, {"wall_s": round(wall, 3), "tokens": toks,
                      "tokens_per_s": round(toks / wall, 2) if wall
                      else 0.0,
                      "peak_concurrent_rows": peak[0]}

    def leak_free(gen) -> bool:
        kv = gen.stats()["kv_pool"]
        return kv["blocks_free"] + kv["radix_nodes"] >= kv["blocks_total"]

    results = {
        "model": model, "tp": tp, "block_size": block_size,
        "max_seq": max_seq, "single_max_seq": single_max_seq,
        "blocks_per_device": blocks_per_device,
        "kv_budget_bytes_per_device": int(blocks_per_device * bpb),
        "n_requests": n_requests, "max_new": max_new,
    }

    # -- facet 1: the model+KV footprint a single chip refuses ---------
    refusal = None
    try:
        ContinuousGenerator(
            spec, params=params, dtype="float32", n_slots=n_slots,
            max_seq=max_seq, prefill_chunk=block_size, mixed_step=True,
            kv_block_size=block_size,
            kv_blocks=blocks_per_device + 1,  # +1: the null block
            prefix_sharing=False)
    except ValueError as exc:
        refusal = str(exc)
    results["single_device_refusal"] = refusal
    ok_refused = refusal is not None and "max_seq row" in refusal

    tp_gen = ContinuousGenerator(
        spec, params=params, dtype="float32", n_slots=n_slots,
        max_seq=max_seq, prefill_chunk=block_size, mixed_step=True,
        kv_block_size=block_size, kv_blocks=tp * blocks_per_device + 1,
        prefix_sharing=False, tp=tp)
    try:
        results["tp_param_bytes_per_device"] = param_bytes_per_device(
            tp_gen.params)
        results["single_param_bytes_per_device"] = \
            param_bytes_per_device(params)
        tp_gen.generate([short_prompts[0][:8]], max_new_tokens=2)  # warm
        long1 = tp_gen.generate([long_prompt], max_new_tokens=max_new)
        long2 = tp_gen.generate([long_prompt], max_new_tokens=max_new)
        s1, r1 = run_burst(tp_gen, short_prompts)
        s2, r2 = run_burst(tp_gen, short_prompts)
        st = tp_gen.stats()
        m = st["mixed"]
        results["tp_arm"] = {
            "kv_blocks": tp * blocks_per_device,
            "long_stream_tokens": len(long1[0]),
            "ticks": m["ticks"], "dispatches": m["dispatches"],
            **r1,
        }
        results["tp_arm"]["peak_concurrent_rows"] = max(
            r1["peak_concurrent_rows"], r2["peak_concurrent_rows"])
        tp_deterministic = (s1 == s2 and long1 == long2)
        tp_single_dispatch = m["ticks"] == m["dispatches"]
        tp_leaks = leak_free(tp_gen)
        tp_long_complete = len(long1[0]) == max_new
    finally:
        tp_gen.stop()

    # -- identity reference: an UNCONSTRAINED single-device lane -------
    # (ample blocks — exists only to prove the TP arm's streams are
    # byte-identical to single-device serving; the budget-constrained
    # single arm below cannot serve max_seq=256 at all).
    ref_gen = ContinuousGenerator(
        spec, params=params, dtype="float32", n_slots=n_slots,
        max_seq=max_seq, prefill_chunk=block_size, mixed_step=True,
        kv_block_size=block_size, prefix_sharing=False)
    try:
        ref_long = ref_gen.generate([long_prompt], max_new_tokens=max_new)
        ref_short, _ = run_burst(ref_gen, short_prompts)
    finally:
        ref_gen.stop()
    streams_identical = (s1 == ref_short and long1 == ref_long)

    # -- facet 2: capacity at equal per-device budget ------------------
    # The single-device arm only exists at this budget by SHRINKING its
    # context window (single_max_seq) — the honest comparison point.
    single_gen = ContinuousGenerator(
        spec, params=params, dtype="float32", n_slots=n_slots,
        max_seq=single_max_seq, prefill_chunk=block_size,
        mixed_step=True, kv_block_size=block_size,
        kv_blocks=blocks_per_device + 1, prefix_sharing=False)
    try:
        single_gen.generate([short_prompts[0][:8]], max_new_tokens=2)
        t1, q1 = run_burst(single_gen, short_prompts)
        t2, q2 = run_burst(single_gen, short_prompts)
        single_deterministic = t1 == t2
        single_leaks = leak_free(single_gen)
        # Full-length streams only: the pool must have bound at
        # admission (parked), never by mid-stream starvation.
        streams_complete = (all(len(o) == max_new for o in t1 + t2)
                            and all(len(o) == max_new for o in s1 + s2))
        results["single_arm"] = {
            "kv_blocks": blocks_per_device, "max_seq": single_max_seq,
            **q1,
        }
        results["single_arm"]["peak_concurrent_rows"] = max(
            q1["peak_concurrent_rows"], q2["peak_concurrent_rows"])
    finally:
        single_gen.stop()

    tp_peak = results["tp_arm"]["peak_concurrent_rows"]
    single_peak = results["single_arm"]["peak_concurrent_rows"]
    results["peak_rows_gain"] = round(tp_peak / max(1, single_peak), 2)
    results["param_bytes_per_device_ratio"] = round(
        results["single_param_bytes_per_device"]
        / max(1, results["tp_param_bytes_per_device"]), 2)
    results["checks_passed"] = bool(
        # The single chip provably refuses the model+KV footprint...
        ok_refused
        # ...the TP arm serves it to completion at the same per-device
        # budget...
        and tp_long_complete
        # ...byte-identically to single-device serving...
        and streams_identical
        # ...with exactly one SPMD dispatch per tick...
        and tp_single_dispatch
        # ...deterministically on both arms, full-length streams
        # (admission-bound pools, no starved early completions), zero
        # blocks leaked...
        and tp_deterministic and single_deterministic
        and streams_complete
        and tp_leaks and single_leaks
        # ...and more concurrent rows on the pooled blocks.
        and tp_peak > single_peak)
    return results


def run_mixed_ab(model: str = "gpt2-small-test", n_short: int = 12,
                 n_long: int = 4, max_new: int = 40, long_max_new: int = 4,
                 short_prompt_len: int = 8, long_prompt_len: int = 440,
                 mean_gap_ms: float = 25.0, dtype: str = "float32",
                 block_size: int = 16, max_seq: int = 512,
                 step_chunk: int = 8, prefill_chunk: int = 256,
                 mixed_budget: int = 16, n_slots: int = 4,
                 model_kwargs: Optional[dict] = None,
                 repeats: int = 2) -> dict:
    """Mixed stepping vs the two-thread paged scheduler under long-prompt
    interference (the --mixed-step tentpole A/B). Workload: Poisson
    arrivals of short decode-heavy requests with long prompts injected
    between them — the pattern whose admission prefills head-of-line
    block decode dispatches in the two-path scheduler. Both arms run the
    SAME paged pool, prompts, seeds, and arrival gaps; only the stepping
    differs. Reports, per arm:

    - ITL p50/p99 over the short rows' token inter-arrival gaps (each
      delivery's gap is charged to its first token, 0 to the rest —
      exactly what a streaming client sees), TTFT p50/p99, tokens/s;
    - device dispatches per generated token, from the scheduler's own
      counters (baseline: decode chunks + admission dispatches; mixed:
      the per-tick ragged dispatch);
    - one-dispatch-per-tick asserted from the mixed stats (ticks and
      dispatches are counted at different code sites).

    A seeded-identity check reruns two prompts on a DENSE scheduler and
    requires byte-identical streams from the mixed arm. CPU mesh by
    default; the on-chip campaign's `mixed` stage reruns it on the
    device."""
    import random

    import jax

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.scheduler import ContinuousGenerator

    _ensure_builtin_models_imported()
    # The registry test model's default geometry is dispatch-overhead-
    # dominated on CPU (a 16-wide tick costs less than a scheduler
    # wakeup), which buries the admission-interference signal in noise —
    # by default the scenario sizes it up (d256 x 4 layers) so compute,
    # not jitter, is measured. `model_kwargs={}` keeps the tiny
    # geometry (the --quick smoke).
    if model_kwargs is None and model == "gpt2-small-test":
        model_kwargs = dict(d_model=256, n_layers=4, n_heads=8,
                            d_ff=1024, vocab=2048)
    spec = create_model(model, max_seq=max_seq, **(model_kwargs or {}))
    params = spec.init(jax.random.PRNGKey(0))
    rnd = random.Random(42)
    width = -(-max_seq // block_size)
    kv_blocks = n_slots * width + 1

    # One interleaved arrival schedule: a long prompt after every
    # n_short//n_long short requests. (kind, prompt, max_new, seed)
    shorts = [[rnd.randrange(1, 200) for _ in range(short_prompt_len)]
              for _ in range(n_short)]
    longs = [[rnd.randrange(1, 200) for _ in range(long_prompt_len)]
             for _ in range(n_long)]
    schedule = []
    li, stride = 0, max(1, n_short // max(1, n_long))
    for i, p in enumerate(shorts):
        schedule.append(("short", p, max_new, 100 + i))
        if (i + 1) % stride == 0 and li < n_long:
            schedule.append(("long", longs[li], long_max_new, 500 + li))
            li += 1
    gaps = [rnd.expovariate(1000.0 / mean_gap_ms) / 1000.0
            for _ in schedule]

    # The shared nearest-rank helper — one definition with /trace's
    # summary percentiles, so the bench's p50/p99 and the server's agree.
    from tpu_engine.utils.tracing import percentile

    import queue as _q

    class _StampQueue(_q.Queue):
        """Stream queue that timestamps each delivery AT put() — i.e. on
        the scheduler's decode thread. ITL measured here is the server's
        actual emission cadence; a consumer thread per request would add
        GIL-wakeup jitter of the same magnitude as a tick and measure
        the load generator instead of the scheduler."""

        def __init__(self):
            super().__init__()
            self.stamps: list = []

        def put(self, item, **kw):
            if item is not None:
                self.stamps.append((time.perf_counter(), len(item)))
            super().put(item, **kw)

    def run_arm(mixed: bool) -> Tuple[dict, list]:
        gen = ContinuousGenerator(
            spec, params=params, dtype=dtype, n_slots=n_slots,
            step_chunk=step_chunk, max_seq=max_seq,
            kv_block_size=block_size, kv_blocks=kv_blocks,
            prefill_chunk=prefill_chunk, prefix_sharing=False,
            mixed_step=mixed,
            mixed_token_budget=mixed_budget if mixed else 0)
        try:
            # Warm every compiled width outside the timed window (short
            # bucket, long bucket, decode, and the mixed tick widths) —
            # then SNAPSHOT the lifetime dispatch counters so the
            # warm-up's dispatches and tokens stay out of BOTH sides of
            # the dispatches-per-token ratio.
            gen.generate([shorts[0]], max_new_tokens=2)
            gen.generate([longs[0][:long_prompt_len]], max_new_tokens=2)
            warm = gen.stats()

            futs, queues, submit_ts = [], [], []
            t0 = time.perf_counter()
            for i, (kind, prompt, mn, seed) in enumerate(schedule):
                time.sleep(gaps[i])
                q = _StampQueue()
                queues.append(q)
                submit_ts.append(time.perf_counter())
                futs.append(gen.submit(prompt, max_new_tokens=mn,
                                       temperature=0.7, seed=seed,
                                       stream=q))
            outs = [f.result(600) for f in futs]
            wall = time.perf_counter() - t0
            st = gen.stats()
        finally:
            gen.stop()

        itl, ttft = [], []
        for i, (kind, _p, _mn, _s) in enumerate(schedule):
            stamps = queues[i].stamps
            if kind != "short" or not stamps:
                continue
            ttft.append(stamps[0][0] - submit_ts[i])
            prev = stamps[0][0]
            for t, n in stamps[1:]:
                itl.append(t - prev)          # charged to the 1st token
                itl.extend([0.0] * (n - 1))
                prev = t
        itl.sort()
        ttft.sort()
        tokens = sum(len(o) for o in outs)
        if mixed:
            m, m0 = st["mixed"], warm["mixed"]
            dispatches = m["dispatches"] - m0["dispatches"]
            new_tokens = (m["decode_tokens"] + m["prefill_tokens"]
                          - m0["decode_tokens"] - m0["prefill_tokens"])
        else:
            dispatches = (st.get("chunks", 0) - warm.get("chunks", 0)
                          + st.get("admission_dispatches", 0)
                          - warm.get("admission_dispatches", 0))
            new_tokens = tokens + sum(len(p) for _k, p, _m, _s in schedule)
        arm = {
            "itl_p50_ms": round((percentile(itl, 50) or 0) * 1e3, 2),
            "itl_p99_ms": round((percentile(itl, 99) or 0) * 1e3, 2),
            "ttft_p50_ms": round((percentile(ttft, 50) or 0) * 1e3, 2),
            "ttft_p99_ms": round((percentile(ttft, 99) or 0) * 1e3, 2),
            "tokens": tokens,
            "tokens_per_s": round(tokens / wall, 2) if wall else 0.0,
            "wall_s": round(wall, 3),
            "device_dispatches": int(dispatches),
            "dispatches_per_token": round(dispatches / max(1, new_tokens),
                                          4),
        }
        if mixed:
            # Lifetime counters (warm-up included) for the invariant;
            # device_dispatches above is the measured-window count.
            arm["lifetime_ticks"] = m["ticks"]
            arm["lifetime_dispatches"] = m["dispatches"]
            arm["one_dispatch_per_tick"] = (m["dispatches"] == m["ticks"])
            arm["coscheduled_ticks"] = m["coscheduled_ticks"]
            arm["cow_copies"] = st["kv_pool"]["cow_copies"]
        return arm, outs

    results = {"model": model, "model_kwargs": model_kwargs or {},
               "max_seq": max_seq,
               "block_size": block_size, "n_slots": n_slots,
               "step_chunk": step_chunk, "prefill_chunk": prefill_chunk,
               "mixed_token_budget": mixed_budget,
               "workload": {"short": n_short, "long": n_long,
                            "short_prompt_len": short_prompt_len,
                            "long_prompt_len": long_prompt_len,
                            "mean_gap_ms": mean_gap_ms}}
    # Arms alternate and each keeps its lowest-p99 repeat: the two-CPU
    # bench host runs arms sequentially, so a background stall mid-run
    # lands on one arm only — best-of-N per arm is the standard
    # least-external-interference estimate (both arms get the same
    # chance). Stream identity is asserted across EVERY repeat.
    baseline = mixed_arm = None
    base_outs = mixed_outs = None
    streams_stable = True
    for rep in range(max(1, repeats)):
        b_arm, b_o = run_arm(mixed=False)
        m_arm, m_o = run_arm(mixed=True)
        streams_stable &= (b_o == m_o)
        if base_outs is not None:
            streams_stable &= (b_o == base_outs and m_o == mixed_outs)
        base_outs, mixed_outs = b_o, m_o
        if baseline is None or b_arm["itl_p99_ms"] < baseline["itl_p99_ms"]:
            baseline = b_arm
        if (mixed_arm is None
                or m_arm["itl_p99_ms"] < mixed_arm["itl_p99_ms"]):
            mixed_arm = m_arm
        record_partial(f"mixed_ab_rep{rep}",
                       {"baseline_itl_p99_ms": b_arm["itl_p99_ms"],
                        "mixed_itl_p99_ms": m_arm["itl_p99_ms"]})
    results["repeats"] = max(1, repeats)
    results["paged_two_thread"] = baseline
    record_partial("mixed_ab_baseline", baseline)
    results["mixed"] = mixed_arm
    record_partial("mixed_ab_mixed", mixed_arm)

    # Seeded streams must be identical across arms (every repeat) AND vs
    # the dense path.
    results["streams_match_baseline"] = streams_stable
    dense = ContinuousGenerator(spec, params=params, dtype=dtype,
                                n_slots=2, step_chunk=step_chunk,
                                max_seq=max_seq)
    try:
        idx = [0, 1]
        dense_outs = [
            dense.generate([schedule[i][1]],
                           max_new_tokens=schedule[i][2],
                           temperature=0.7, seed=schedule[i][3])[0]
            for i in idx]
        results["streams_match_dense"] = (
            dense_outs == [mixed_outs[i] for i in idx])
    finally:
        dense.stop()
    results["itl_p99_speedup"] = round(
        baseline["itl_p99_ms"] / max(mixed_arm["itl_p99_ms"], 1e-9), 2)
    # p50 of per-token gaps is 0 whenever chunked deliveries dominate
    # (7 of 8 tokens in a chunk arrive at gap 0) — a ratio against it is
    # noise, so it is reported only when both medians are nonzero.
    results["itl_p50_speedup"] = (
        round(baseline["itl_p50_ms"] / mixed_arm["itl_p50_ms"], 2)
        if baseline["itl_p50_ms"] > 0 and mixed_arm["itl_p50_ms"] > 0
        else None)
    results["checks_passed"] = bool(
        mixed_arm.get("one_dispatch_per_tick")
        and results["streams_match_dense"]
        and results["streams_match_baseline"])
    return results


def run_unified_ab(model: str = "gpt2-small-test", n_generate: int = 10,
                   n_score: int = 20, max_new: int = 24,
                   prompt_len: int = 10, score_prompt_len: int = 12,
                   score_completion_len: int = 6,
                   mean_gap_ms: float = 12.0, dtype: str = "float32",
                   n_slots: int = 4, max_seq: int = 256,
                   step_chunk: int = 4,
                   model_kwargs: Optional[dict] = None,
                   repeats: int = 2) -> dict:
    """Unified stateless serving vs the two-lane split (the PR 20
    tentpole A/B). Workload: one Poisson arrival process mixing
    generate streams and score (teacher-forced logprob) requests — the
    mixed-modality traffic ROADMAP item 5 names. Two arms at equal
    resources (same device, same scheduler slot count, same score batch
    cap, same prompts/seeds/arrival gaps):

    - **split**: the continuous scheduler serves generate only; score
      requests ride a dedicated ``BatchProcessor`` lane whose forwards
      run UNCOORDINATED with decode ticks on their own dispatch thread
      (the pre-fold production shape);
    - **unified**: one ``ContinuousGenerator`` with a ``score_provider``
      — scores admit as single-tick rows in the same slot pool and
      dispatch as one grouped forward per tick, interleaved with decode
      by the scheduler itself.

    Reports per arm and class: score latency p50/p99, generate
    completion latency p50/p99 and TTFT p99. Checks: score logprobs and
    generate streams byte-identical across arms AND across every
    repeat; the unified arm's stateless counters hold
    ticks == dispatches (one grouped dispatch per tick with one-shot
    rows in the batch). CPU mesh by default; the on-chip campaign's
    ``unified`` stage reruns it on the device."""
    import random

    import jax

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.batch_processor import BatchProcessor
    from tpu_engine.runtime.generator import Generator
    from tpu_engine.runtime.scheduler import ContinuousGenerator
    from tpu_engine.utils.tracing import percentile

    _ensure_builtin_models_imported()
    # Same sizing rationale as run_mixed_ab: the tiny registry geometry
    # is dispatch-overhead-dominated on CPU; size it up so compute, not
    # scheduler jitter, dominates. model_kwargs={} keeps it tiny
    # (--quick).
    if model_kwargs is None and model == "gpt2-small-test":
        model_kwargs = dict(d_model=256, n_layers=4, n_heads=8,
                            d_ff=1024, vocab=2048)
    spec = create_model(model, max_seq=max_seq, **(model_kwargs or {}))
    params = spec.init(jax.random.PRNGKey(0))
    rnd = random.Random(20)

    # ONE scorer instance serves both arms: shared compiled caches and
    # — by construction — identical bucketed-pad-split numerics, so any
    # cross-arm output difference is a scheduling bug, not jit noise.
    scorer = Generator(spec, params=params, dtype=dtype)

    gens = [[rnd.randrange(1, 200) for _ in range(prompt_len)]
            for _ in range(n_generate)]
    scores = [([rnd.randrange(1, 200) for _ in range(score_prompt_len)],
               [rnd.randrange(1, 200) for _ in range(score_completion_len)])
              for _ in range(n_score)]
    # One interleaved arrival schedule shared by both arms.
    schedule = []
    gi, si = 0, 0
    stride = max(1, n_score // max(1, n_generate))
    while gi < n_generate or si < n_score:
        if gi < n_generate:
            schedule.append(("generate", gi))
            gi += 1
        for _ in range(stride):
            if si < n_score:
                schedule.append(("score", si))
                si += 1
    gaps = [rnd.expovariate(1000.0 / mean_gap_ms) / 1000.0
            for _ in schedule]

    from concurrent.futures import ThreadPoolExecutor
    import queue as _q

    def run_arm(unified: bool) -> Tuple[dict, dict]:
        gen = ContinuousGenerator(
            spec, params=params, dtype=dtype, n_slots=n_slots,
            step_chunk=step_chunk, max_seq=max_seq,
            score_provider=(lambda: scorer) if unified else None)
        proc = None
        if not unified:
            # The retired lane: its own dispatch thread, its own queue,
            # equal batch cap — forwards land whenever they form,
            # uncoordinated with the scheduler's ticks.
            proc = BatchProcessor(
                n_slots, 5.0,
                lambda items: scorer.score([p for p, _c in items],
                                           [c for _p, c in items]),
                name="split-score-lane")
            proc.start()
        try:
            # Warm every compiled path outside the timed window — decode
            # at full slot width, and the scorer at every batch width a
            # grouped dispatch (either arm's) can form. A mid-run jit
            # compile would land on different threads in the two arms
            # (side lane vs decode loop) and measure XLA, not
            # scheduling.
            gen.generate([gens[i % len(gens)] for i in range(n_slots)],
                         max_new_tokens=2)
            for k in range(1, n_slots + 1):
                scorer.score([scores[0][0]] * k, [scores[0][1]] * k)
            if unified:
                gen.submit_score(*scores[0]).result(120)
            warm = gen.stats()

            g_lat = [None] * n_generate
            g_ttft = [None] * n_generate
            g_out = [None] * n_generate
            s_lat = [None] * n_score
            s_out = [None] * n_score

            def score_call(idx, t_sub):
                p, c = scores[idx]
                if unified:
                    lps, _us = gen.submit_score(p, c).result(600)
                else:
                    lps = proc.process((p, c))
                s_lat[idx] = time.perf_counter() - t_sub
                s_out[idx] = list(lps)

            with ThreadPoolExecutor(max_workers=8) as ex:
                futs, sfuts = [], []
                t0 = time.perf_counter()
                for i, (kind, idx) in enumerate(schedule):
                    time.sleep(gaps[i])
                    t_sub = time.perf_counter()
                    if kind == "generate":
                        q = _q.Queue()

                        def first_tok(qq=q, j=idx, ts=t_sub):
                            tok = qq.get(timeout=600)
                            if tok is not None:
                                g_ttft[j] = time.perf_counter() - ts

                        ex.submit(first_tok)
                        futs.append((idx, t_sub,
                                     gen.submit(gens[idx],
                                                max_new_tokens=max_new,
                                                temperature=0.7,
                                                seed=900 + idx,
                                                stream=q)))
                    else:
                        sfuts.append(ex.submit(score_call, idx, t_sub))
                for idx, t_sub, f in futs:
                    g_out[idx] = f.result(600)
                    g_lat[idx] = time.perf_counter() - t_sub
                for f in sfuts:
                    f.result(600)
                wall = time.perf_counter() - t0
            st = gen.stats()
        finally:
            gen.stop()
            if proc is not None:
                proc.stop()

        s_sorted = sorted(s_lat)
        g_sorted = sorted(g_lat)
        ttft_sorted = sorted(t for t in g_ttft if t is not None)
        arm = {
            "score_p50_ms": round((percentile(s_sorted, 50) or 0) * 1e3,
                                  2),
            "score_p99_ms": round((percentile(s_sorted, 99) or 0) * 1e3,
                                  2),
            "generate_p50_ms": round((percentile(g_sorted, 50) or 0)
                                     * 1e3, 2),
            "generate_p99_ms": round((percentile(g_sorted, 99) or 0)
                                     * 1e3, 2),
            "ttft_p99_ms": round((percentile(ttft_sorted, 99) or 0)
                                 * 1e3, 2),
            "wall_s": round(wall, 3),
        }
        if unified:
            su, sw = st["stateless"], warm["stateless"]
            arm["stateless_ticks"] = su["ticks"] - sw["ticks"]
            arm["stateless_dispatches"] = (su["dispatches"]
                                           - sw["dispatches"])
            arm["score_rows"] = su["score_rows"] - sw["score_rows"]
            # One grouped dispatch per tick with one-shot rows in the
            # batch — the ticks==dispatches invariant, counted at two
            # different code sites (lifetime counters).
            arm["ticks_eq_dispatches"] = (su["ticks"] == su["dispatches"])
        return arm, {"gen": g_out, "score": s_out}

    results = {"model": model, "model_kwargs": model_kwargs or {},
               "n_slots": n_slots, "step_chunk": step_chunk,
               "max_seq": max_seq,
               "workload": {"generate": n_generate, "score": n_score,
                            "max_new": max_new,
                            "prompt_len": prompt_len,
                            "score_prompt_len": score_prompt_len,
                            "score_completion_len": score_completion_len,
                            "mean_gap_ms": mean_gap_ms}}
    # Arms alternate; each keeps its lowest-p99 repeat (the same
    # best-of-N least-external-interference estimate every AB scenario
    # here uses). Output identity is asserted across EVERY repeat and
    # across arms.
    split_arm = unified_arm = None
    prev_outs = None
    identical = True
    for rep in range(max(1, repeats)):
        s_arm, s_o = run_arm(unified=False)
        u_arm, u_o = run_arm(unified=True)
        identical &= (s_o == u_o)
        if prev_outs is not None:
            identical &= (s_o == prev_outs)
        prev_outs = s_o
        if (split_arm is None
                or s_arm["score_p99_ms"] < split_arm["score_p99_ms"]):
            split_arm = s_arm
        if (unified_arm is None
                or u_arm["score_p99_ms"] < unified_arm["score_p99_ms"]):
            unified_arm = u_arm
        record_partial(f"unified_ab_rep{rep}",
                       {"split_score_p99_ms": s_arm["score_p99_ms"],
                        "unified_score_p99_ms": u_arm["score_p99_ms"],
                        "split_generate_p99_ms":
                            s_arm["generate_p99_ms"],
                        "unified_generate_p99_ms":
                            u_arm["generate_p99_ms"]})
    results["repeats"] = max(1, repeats)
    results["split"] = split_arm
    results["unified"] = unified_arm
    record_partial("unified_ab_split", split_arm)
    record_partial("unified_ab_unified", unified_arm)
    results["outputs_identical"] = identical
    results["score_p99_speedup"] = round(
        split_arm["score_p99_ms"]
        / max(unified_arm["score_p99_ms"], 1e-9), 2)
    results["generate_p99_speedup"] = round(
        split_arm["generate_p99_ms"]
        / max(unified_arm["generate_p99_ms"], 1e-9), 2)
    results["checks_passed"] = bool(
        identical and unified_arm.get("ticks_eq_dispatches")
        and results["score_p99_speedup"] >= 1.0
        and results["generate_p99_speedup"] >= 1.0)
    return results


def run_spec_continuous_ab(model: str = "gpt2-small-test",
                           max_new: int = 96, k: int = 4,
                           dtype: str = "float32", block_size: int = 16,
                           max_seq: int = 256, n_slots: int = 4,
                           step_chunk: int = 8, prefill_chunk: int = 32,
                           model_kwargs: Optional[dict] = None,
                           prompts: Optional[list] = None) -> dict:
    """Continuous speculative decoding vs the plain paged scheduler
    (the --spec-k tentpole A/B) — COUNTER-based, not wall-clock: the
    speculation win is sequential target passes per token, and the
    scheduler's own counters state it exactly.

    Workload: repetitive greedy streams (prompts whose continuations
    loop — the repeated-text regime prompt-lookup drafting exists for;
    retrieval-stuffed prompts and code behave this way on real models).
    Both arms run the same paged pool, prompts, and seeds; the spec arm
    adds the n-gram drafter with depth ``k``. Reports:

    - tokens_per_row_dispatch (same name as the scheduler stat): emitted
      tokens — accepted draft prefix + the corrected/bonus token — per
      (row, tick) emission pair from the spec arm's counters, i.e. the
      mean per-row stream advance per verify dispatch. NOT the raw
      `accepted_tokens` counter, which counts draft-accepted slots only.
      The plain scheduler advances every row exactly 1 token per
      sequential target pass, so this IS the speedup ratio in sequential
      passes (asserted >= 1.5x here);
    - one-dispatch-per-tick from the spec stats (ticks and dispatches
      are counted at different code sites);
    - byte-identical greedy streams spec vs plain vs a dense rerun;
    - a mid-run deadline-cancelled row returns every pool block.

    Wall-clock tokens/s are reported for color only — on the CPU mesh
    the verify window's extra host work can mask the dispatch saving
    that dominates on a real chip (the on-chip campaign's `spec` stage
    reruns this there)."""
    import jax

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.scheduler import ContinuousGenerator
    from tpu_engine.utils.deadline import Deadline, DeadlineExceeded

    _ensure_builtin_models_imported()
    spec = create_model(model, max_seq=max_seq, **(model_kwargs or {}))
    params = spec.init(jax.random.PRNGKey(0))
    if prompts is None:
        # Probed loopy-continuation prompts for the registry test model
        # (streams with 0.5-0.7 three-gram predictability — the
        # "repetitive workload"); other models get phrase-repeat prompts.
        if model == "gpt2-small-test" and not model_kwargs:
            base = [[153, 128, 149, 117, 18, 24], [128, 175, 137, 110],
                    [135, 127, 88, 187, 115, 74],
                    [122, 179, 171, 17, 16, 188],
                    [10, 23, 112, 108], [120, 150, 117, 93, 77, 64]]
            prompts = base + base[:2]
        else:
            import random as _r
            rnd = _r.Random(42)
            prompts = [([rnd.randrange(1, min(spec.config.vocab, 1000))
                         for _ in range(6)] * 5)[:24] for _ in range(8)]
    width = -(-max_seq // block_size)
    kv_blocks = n_slots * width + 1
    common_kw = dict(params=params, dtype=dtype, n_slots=n_slots,
                     step_chunk=step_chunk, max_seq=max_seq,
                     kv_block_size=block_size, kv_blocks=kv_blocks,
                     prefill_chunk=prefill_chunk)

    def run_arm(spec_k: int) -> Tuple[dict, list]:
        gen = ContinuousGenerator(spec, spec_k=spec_k, **common_kw)
        try:
            gen.generate([prompts[0]], max_new_tokens=4)  # warm compiles
            warm = gen.stats()
            t0 = time.perf_counter()
            outs = gen.generate(prompts, max_new_tokens=max_new)
            wall = time.perf_counter() - t0
            st = gen.stats()
            arm = {"tokens": sum(len(o) for o in outs),
                   "wall_s": round(wall, 3),
                   "tokens_per_s": round(sum(len(o) for o in outs)
                                         / wall, 2) if wall else 0.0}
            if spec_k:
                s, s0 = st["spec"], warm["spec"]
                emitted = s["emitted_tokens"] - s0["emitted_tokens"]
                row_ticks = s["row_ticks"] - s0["row_ticks"]
                arm.update({
                    "spec_dispatches": s["dispatches"] - s0["dispatches"],
                    "proposed_tokens": (s["proposed_tokens"]
                                        - s0["proposed_tokens"]),
                    "accepted_tokens": (s["accepted_tokens"]
                                        - s0["accepted_tokens"]),
                    "emitted_tokens": emitted,
                    "row_dispatches": row_ticks,
                    "tokens_per_row_dispatch": round(
                        emitted / max(1, row_ticks), 3),
                    "accept_ratio": round(
                        (s["accepted_tokens"] - s0["accepted_tokens"])
                        / max(1, s["proposed_tokens"]
                              - s0["proposed_tokens"]), 3),
                    "one_dispatch_per_tick": (s["ticks"]
                                              == s["dispatches"]),
                })
                # Cancelled-row block return, validated on the live
                # scheduler: a doomed long request expires between verify
                # ticks and must hand every block back.
                try:
                    gen.submit(prompts[0] * 3, max_new_tokens=max_new,
                               deadline=Deadline.after_ms(1)).result(60)
                    arm["cancelled_row_expired"] = False
                except DeadlineExceeded:
                    arm["cancelled_row_expired"] = True
                deadline = time.time() + 15
                returned = False
                while time.time() < deadline and not returned:
                    stt = gen.stats()
                    pool = stt["kv_pool"]
                    returned = (stt["active"] == 0
                                and pool["blocks_free"]
                                + pool["radix_nodes"]
                                >= pool["blocks_total"])
                    if not returned:
                        time.sleep(0.05)
                arm["cancelled_row_blocks_returned"] = returned
            return arm, outs
        finally:
            gen.stop()

    results = {"model": model, "max_seq": max_seq, "k": k,
               "block_size": block_size, "n_slots": n_slots,
               "max_new_tokens": max_new, "n_prompts": len(prompts),
               "draft": "ngram"}
    plain_arm, plain_outs = run_arm(0)
    record_partial("spec_cont_plain", plain_arm)
    spec_arm, spec_outs = run_arm(k)
    record_partial("spec_cont_spec", spec_arm)
    results["plain_paged"] = plain_arm
    results["spec"] = spec_arm
    results["streams_match_plain"] = spec_outs == plain_outs

    # Dense cross-check on two prompts: the spec arm's streams are the
    # DENSE scheduler's too (transitively pins all three layouts).
    dense = ContinuousGenerator(spec, params=params, dtype=dtype,
                                n_slots=2, step_chunk=step_chunk,
                                max_seq=max_seq)
    try:
        dense_outs = [dense.generate([prompts[i]],
                                     max_new_tokens=max_new)[0]
                      for i in (0, 1)]
        results["streams_match_dense"] = (
            dense_outs == [spec_outs[i] for i in (0, 1)])
    finally:
        dense.stop()
    ratio = spec_arm["tokens_per_row_dispatch"]
    # The plain scheduler advances 1 token per row per sequential target
    # pass by construction — `ratio` IS the sequential-pass speedup.
    results["tokens_per_dispatch_ratio"] = ratio
    results["checks_passed"] = bool(
        ratio >= 1.5
        and spec_arm["one_dispatch_per_tick"]
        and spec_arm["cancelled_row_expired"]
        and spec_arm["cancelled_row_blocks_returned"]
        and results["streams_match_plain"]
        and results["streams_match_dense"])
    return results


def run_crash_ab(n_streams: int = 12, max_new: int = 48,
                 model: str = "gpt2-small-test") -> dict:
    """Crash-tolerant streaming A/B (DESIGN.md "Crash-tolerant
    streaming"): kill -9 a worker process while its /generate/stream
    load is mid-generation, with the gateway's stream journal + health
    prober ON vs OFF.

    Four standalone worker processes are spawned once; each arm routes
    across three of them through an in-process gateway and kills that
    arm's designated victim the moment a victim-primary stream is
    provably mid-flight. Reported per arm:

    - stream_completion_rate: streams ending in a clean terminal event;
    - identical_rate: streams byte-identical to an unkilled blocking
      control run (greedy AND seeded-sampled — the resume determinism
      rule);
    - availability: short blocking /generate probes fired AFTER the kill
      (ring failover answers these in both arms; the prober just makes
      the dead lane invisible sooner);
    - resumed_streams / prober_ejections (ON arm only).

    The A/B criterion: failover ON completes and matches 100% of
    streams; OFF loses exactly the mid-flight victim streams — the
    measured cost of binding a request to a lane instead of the fleet."""
    import random
    import signal

    from tools.fault_injection import (
        control_oracle,
        drive_streams_with_kill,
        launch_worker_procs,
        rid_for_lane,
        tally_streams,
        victim_lane_for_port,
    )
    from tpu_engine.serving.gateway import Gateway
    from tpu_engine.utils.config import GatewayConfig

    ports, procs = launch_worker_procs(4)
    try:
        def run_arm(indices, victim_idx, failover: bool) -> dict:
            gw = Gateway(
                [f"127.0.0.1:{ports[i]}" for i in indices],
                GatewayConfig(
                    failover_streams=failover,
                    health_probe_interval_s=0.25 if failover else 0.0,
                    health_probe_failures=2))
            try:
                lanes = gw.worker_names()
                victim_lane = victim_lane_for_port(
                    lanes, ports[victim_idx])

                requests = []
                for k in range(n_streams):
                    lane = (victim_lane if k % 3 == 0
                            else lanes[k % len(lanes)])
                    params = ({} if k % 2 == 0
                              else {"temperature": 0.9, "seed": 300 + k})
                    tag = f"{'on' if failover else 'off'}{k}"
                    requests.append({
                        "request_id": rid_for_lane(gw._ring, lane, tag),
                        "prompt_tokens": [(k * 11 + j) % 90 + 1
                                          for j in range(5 + k % 4)],
                        "max_new_tokens": (max_new + 12
                                           if lane == victim_lane
                                           else max_new),
                        **params})
                victim_rids = {r["request_id"] for r in requests
                               if gw._ring.get_node(r["request_id"])
                               == victim_lane}
                control = control_oracle(ports[0], requests)

                def kill_victim():
                    procs[victim_idx].send_signal(signal.SIGKILL)
                    procs[victim_idx].wait(timeout=10)

                results, killed = drive_streams_with_kill(
                    gw, requests, victim_rids, kill_victim,
                    random.Random(1 if failover else 2))
                # Availability AFTER the kill: short blocking probes;
                # ring failover answers them in both arms.
                avail_ok = 0
                for i in range(6):
                    try:
                        gw.route_generate(
                            {"request_id": f"avail_{failover}_{i}",
                             "prompt_tokens": [7, i + 1],
                             "max_new_tokens": 4})
                        avail_ok += 1
                    except Exception:
                        pass
                complete, identical, resumed = tally_streams(
                    results, control)
                fo = gw.get_stats().get("failover", {})
                return {
                    "failover": failover, "streams": len(requests),
                    "victim_primary_streams": len(victim_rids),
                    "victim_killed_mid_stream": killed,
                    "completed": complete,
                    "stream_completion_rate": round(
                        complete / len(requests), 3),
                    "identical": identical,
                    "identical_rate": round(
                        identical / len(requests), 3),
                    "availability_post_kill": round(avail_ok / 6, 3),
                    "resumed_streams": resumed,
                    "resumes_attempted": fo.get("resumes_attempted", 0),
                    "tokens_replayed": fo.get("tokens_replayed", 0),
                    "prober_ejections": fo.get("prober_ejections", 0),
                }
            finally:
                gw.stop()

        on = run_arm([0, 1, 2], 1, True)
        record_partial("crash_on", on)
        off = run_arm([0, 2, 3], 3, False)
        record_partial("crash_off", off)
        results = {"model": model, "n_streams_per_arm": n_streams,
                   "failover_on": on, "failover_off": off}
        results["checks_passed"] = bool(
            on["victim_killed_mid_stream"]
            and off["victim_killed_mid_stream"]
            and on["stream_completion_rate"] == 1.0
            and on["identical_rate"] == 1.0
            and on["resumed_streams"] >= 1
            and on["prober_ejections"] >= 1
            and off["stream_completion_rate"] < 1.0)
        return results
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def run_drain_ab(n_streams: int = 10, max_new: int = 48,
                 model: str = "gpt2-small-test") -> dict:
    """Live stream migration A/B (DESIGN.md "Live stream migration"):
    drain a LOADED lane mid-stream with ``--migrate-streams`` ON (KV
    block handoff: export the row's chain + state, import on another
    lane, zero re-prefilled tokens) vs OFF (today's shed + PR 6 replay:
    full re-prefill of prompt ⧺ emitted on the resume lane).

    Both arms model the rolling-restart reality: the lane is drained
    and its PROCESS IS KILLED shortly after (the maintenance window
    closes — a fleet cannot wait out its longest stream). With
    migration on, remove_worker has already evacuated every journaled
    stream by then (the kill finds nothing to lose); without it, the
    kill truncates the still-running lame-duck streams and PR 6 replays
    them — full re-prefill of prompt ⧺ emitted on the resume lane.

    Four standalone worker processes are spawned once; each arm routes
    across three through an in-process gateway and drains+kills that
    arm's victim the moment a victim-primary stream is provably
    mid-flight. Reported per arm:

    - stream_completion_rate / identical_rate vs an unkilled blocking
      control (greedy AND seeded — the splice determinism rule);
    - reprefill_tokens: tokens_replayed (re-prefixed into resume
      prompts — the replay arm's prefill burden) plus the survivors'
      measured prefilled_tokens delta across the drain window;
    - migrated_rows / imported_rows (ON arm: >= 1, fallbacks 0);
    - post-drain TTFT and ITL p50/p99 over short probe streams fired
      after the drain settles (the fleet is 2/3 its size either way;
      migration must not leave it slower than replay did).

    The A/B criterion: the migrate arm completes 100% byte-identical
    with ZERO replay tokens (migrated rows re-prefill nothing); the
    replay arm completes too (failover is on in both arms) but pays
    tokens_replayed > 0 of re-prefix prefill."""
    import random
    import signal
    import threading

    from tools.fault_injection import (
        _call,
        control_oracle,
        drive_streams_with_kill,
        launch_worker_procs,
        rid_for_lane,
        tally_streams,
        victim_lane_for_port,
    )
    from tpu_engine.serving.gateway import Gateway, _parse_sse
    from tpu_engine.utils.config import GatewayConfig
    from tpu_engine.utils.tracing import percentile

    ports, procs = launch_worker_procs(
        4, extra_args=("--kv-blocks", "48"))

    def lane_prefilled(port: int) -> int:
        try:
            _, health = _call(port, "GET", "/health", timeout=30)
            return ((health.get("generator") or {})
                    .get("kv_pool") or {}).get("prefilled_tokens", 0)
        except Exception:
            return 0

    try:
        def run_arm(indices, victim_idx, migrate: bool) -> dict:
            gw = Gateway(
                [f"127.0.0.1:{ports[i]}" for i in indices],
                GatewayConfig(
                    failover_streams=True,
                    migrate_streams=migrate,
                    migrate_timeout_s=60.0,
                    health_probe_interval_s=0.25,
                    health_probe_failures=2))
            try:
                lanes = gw.worker_names()
                victim_lane = victim_lane_for_port(lanes,
                                                   ports[victim_idx])
                survivor_ports = [ports[i] for i in indices
                                  if ports[i] != ports[victim_idx]]
                requests = []
                for k in range(n_streams):
                    lane = (victim_lane if k % 3 == 0
                            else lanes[k % len(lanes)])
                    params = ({} if k % 2 == 0
                              else {"temperature": 0.9, "seed": 700 + k})
                    tag = f"{'mig' if migrate else 'rep'}{k}"
                    # Victim streams run LONG (4x) so every one is
                    # still mid-flight when the drain+kill sequence
                    # lands — the case migration exists for
                    # (kill_when="all" below waits for that).
                    requests.append({
                        "request_id": rid_for_lane(gw._ring, lane, tag),
                        "prompt_tokens": [(k * 11 + j) % 90 + 1
                                          for j in range(5 + k % 4)],
                        "max_new_tokens": (max_new * 4
                                           if lane == victim_lane
                                           else max_new),
                        **params})
                victim_rids = {r["request_id"] for r in requests
                               if gw._ring.get_node(r["request_id"])
                               == victim_lane}
                control = control_oracle(ports[indices[0]], requests)

                def survivors_imported() -> int:
                    total = 0
                    for p in survivor_ports:
                        try:
                            _, health = _call(p, "GET", "/health",
                                              timeout=30)
                        except Exception:
                            continue
                        gmig = ((health.get("generator") or {})
                                .get("migration") or {})
                        total += gmig.get("imported_rows", 0)
                    return total

                pre_prefill = {"v": None}
                imported_before = survivors_imported()

                def drain_and_kill():
                    # Snapshot the survivors' prefill counters at the
                    # drain instant: everything they prefill AFTER this
                    # is resume/migration burden (admissions were all
                    # dispatched before the drain window closes).
                    pre_prefill["v"] = sum(lane_prefilled(p)
                                           for p in survivor_ports)
                    gw.remove_worker(victim_lane, drain=True)
                    # The maintenance window closes: the process goes
                    # away either way, IMMEDIATELY after the drain call
                    # returns. Migrate mode has evacuated every
                    # journaled stream by then (remove_worker blocks on
                    # the transfers and handoff pickup); without it the
                    # kill truncates the still-running lame-duck
                    # streams and the journal replays them.
                    procs[victim_idx].send_signal(signal.SIGKILL)
                    procs[victim_idx].wait(timeout=10)

                results, drained = drive_streams_with_kill(
                    gw, requests, victim_rids, drain_and_kill,
                    random.Random(3 if migrate else 4),
                    arrival_rate=30.0, kill_when="all")
                post_prefill = sum(lane_prefilled(p)
                                   for p in survivor_ports)
                complete, identical, resumed = tally_streams(
                    results, control)
                stats = gw.get_stats()
                fo = stats.get("failover", {})
                mig = stats.get("migration", {})
                imported_rows = survivors_imported() - imported_before

                # Post-drain latency probes: short streams on the
                # shrunken fleet; TTFT + inter-token gaps client-side.
                ttfts, gaps = [], []
                for i in range(8):
                    t0 = time.perf_counter()
                    last = None
                    for frame in gw.route_generate_stream(
                            {"request_id": f"probe_{migrate}_{i}",
                             "prompt_tokens": [7, i + 1, 3],
                             "max_new_tokens": 12}):
                        evt = _parse_sse(frame)
                        if not evt or "tokens" not in evt \
                                or evt.get("done"):
                            continue
                        now = time.perf_counter()
                        if last is None:
                            ttfts.append(now - t0)
                        else:
                            gaps.append(now - last)
                        last = now
                return {
                    "migrate": migrate, "streams": len(requests),
                    "victim_primary_streams": len(victim_rids),
                    "drained_mid_stream": drained,
                    "completed": complete,
                    "stream_completion_rate": round(
                        complete / len(requests), 3),
                    "identical": identical,
                    "identical_rate": round(
                        identical / len(requests), 3),
                    "resumed_streams": resumed,
                    "migrated_streams": mig.get("streams_migrated", 0),
                    "migration_fallbacks": mig.get(
                        "migration_fallbacks", 0),
                    "imported_rows": imported_rows,
                    "reprefill_tokens_replayed": fo.get(
                        "tokens_replayed", 0),
                    "reprefill_tokens_measured": (
                        post_prefill - pre_prefill["v"]
                        if pre_prefill["v"] is not None else None),
                    "post_drain_ttft_ms": {
                        "p50": round(1e3 * (percentile(ttfts, 50) or 0),
                                     1),
                        "p99": round(1e3 * (percentile(ttfts, 99) or 0),
                                     1)},
                    "post_drain_itl_ms": {
                        "p50": round(1e3 * (percentile(gaps, 50) or 0),
                                     1),
                        "p99": round(1e3 * (percentile(gaps, 99) or 0),
                                     1)},
                }
            finally:
                gw.stop()

        on = run_arm([0, 1, 2], 1, True)
        record_partial("drain_migrate", on)
        off = run_arm([0, 2, 3], 3, False)
        record_partial("drain_replay", off)
        results = {"model": model, "n_streams_per_arm": n_streams,
                   "migrate_on": on, "replay_off": off}
        results["checks_passed"] = bool(
            on["drained_mid_stream"] and off["drained_mid_stream"]
            and on["stream_completion_rate"] == 1.0
            and on["identical_rate"] == 1.0
            and on["migrated_streams"] >= 1
            and on["migration_fallbacks"] == 0
            and on["reprefill_tokens_replayed"] == 0
            and on["imported_rows"] >= 1
            and off["stream_completion_rate"] == 1.0
            and off["identical_rate"] == 1.0
            and off["resumed_streams"] >= 1
            and off["reprefill_tokens_replayed"] > 0)
        return results
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def run_disagg_ab(model: str = "gpt2-small-test", n_streams: int = 24,
                  max_new: int = 24, prompt_len: int = 230,
                  burst: int = 3, mean_burst_gap_ms: float = 350.0,
                  block_size: int = 16, slots_per_lane: int = 6,
                  max_seq: int = 512, prefill_chunk: int = 128,
                  quick: bool = False) -> dict:
    """Disaggregated prefill/decode serving A/B (the PR 14 tentpole):
    a bursty long-prompt Poisson workload over 4 in-process lanes —
    2 dedicated prefill + 2 dedicated decode behind a ``--disagg``
    gateway vs 4 colocated mixed-step lanes behind a default gateway.

    The mechanism under test: colocated mixed stepping co-schedules
    every in-flight row's decode token with admitting rows' prefill
    chunks in ONE ragged dispatch — a burst of long prompts inflates
    every decode row's inter-token latency by the chunk compute, and
    prefill TTFT queues behind the decode ticks. Disaggregation gives
    each phase its own lanes: prefill lanes run prompt chunks only
    (TTFT no longer waits out decode ticks), park the finished row, and
    ship chain + sampling snapshot to a decode lane (PR 11 wire
    format, zero re-prefilled tokens); decode lanes never co-schedule a
    prefill chunk again (ITL stops absorbing 100+-token chunk
    dispatches). The handoff gap itself lands in the disagg arm's ITL
    sample — the win must survive paying it honestly.

    Reported per arm: client-side TTFT p50/p99 and ITL p50/p99 over
    every stream, stream identity across arms (greedy AND seeded — the
    splice is byte-exact), handoff accounting (spliced == streams,
    fallbacks 0), zero KV blocks leaked on every pool. Bars:
    disagg TTFT p99 AND ITL p99 both beat colocated; defaults-off
    /stats //health byte-identical (no handoff/role keys anywhere);
    a quantized (int8) split fleet hands off verbatim with no
    requantization. CPU mesh (tiny registry model — phase-interference
    and handoff-cost shapes, not model-size properties); on-chip rerun
    pending like r06-r13."""
    import queue as _q
    import random
    import threading

    import jax

    from tpu_engine.models.registry import (
        _ensure_builtin_models_imported, create_model)
    from tpu_engine.runtime.engine import InferenceEngine
    from tpu_engine.serving.gateway import Gateway, _parse_sse
    from tpu_engine.serving.worker import WorkerNode
    from tpu_engine.utils.config import GatewayConfig, WorkerConfig
    from tpu_engine.utils.tracing import percentile

    _ensure_builtin_models_imported()
    if quick:
        n_streams, prompt_len, max_seq = 12, 110, 256
        prefill_chunk = 64
    spec = create_model(model, max_seq=max_seq)
    params = spec.init(jax.random.PRNGKey(0))
    rnd = random.Random(29)
    requests = []
    for i in range(n_streams):
        params_i = ({} if i % 2 == 0
                    else {"temperature": 0.8, "seed": 900 + i})
        requests.append({
            "request_id": f"dg-{i}",
            "prompt_tokens": [rnd.randrange(1, 200)
                              for _ in range(prompt_len + (i % 7))],
            "max_new_tokens": max_new, **params_i})
    # Bursty Poisson: arrivals land in bursts of `burst` streams, burst
    # gaps exponential — several long prompts hit the fleet at once,
    # the interference shape disaggregation exists for.
    gaps = []
    for i in range(n_streams):
        gaps.append(0.0 if i % burst else
                    rnd.expovariate(1000.0 / mean_burst_gap_ms) / 1000.0)

    # Equal FLEET resources, role-shaped: the colocated arm spreads
    # rows over 4 lanes; the disagg arm concentrates decode rows on 2,
    # so an operator provisions decode lanes with more slots + pool and
    # prefill lanes (rows exported moments after prefill) with less —
    # both arms get the same total slots and total KV blocks.
    bucket = 16
    while bucket < prompt_len + 8:
        bucket *= 2
    blocks_per_row = bucket // block_size + 3
    colo_blocks = slots_per_lane * blocks_per_row + 36
    prefill_slots = max(2, slots_per_lane - 2)
    prefill_blocks = prefill_slots * blocks_per_row + 20
    decode_slots = 2 * slots_per_lane - prefill_slots
    decode_blocks = (4 * colo_blocks - 2 * prefill_blocks) // 2
    shapes = {"both": (slots_per_lane, colo_blocks),
              "prefill": (prefill_slots, prefill_blocks),
              "decode": (decode_slots, decode_blocks)}

    def make_fleet(roles):
        workers = []
        for i, role in enumerate(roles):
            slots, blocks = shapes[role]
            cfg = WorkerConfig(
                node_id=f"lane_{i+1}", model=model, role=role,
                gen_max_batch_size=slots, gen_step_chunk=4,
                gen_prefix_cache_mb=0, gen_kv_block_size=block_size,
                gen_kv_blocks=blocks, gen_mixed_step=True,
                gen_prefill_chunk=prefill_chunk)
            engine = InferenceEngine(spec, params=params, dtype="float32")
            workers.append(WorkerNode(cfg, engine=engine))
        return workers

    def leak_free(workers):
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            ok = True
            for w in workers:
                st = w.generator.stats()
                kp = st["kv_pool"]
                if (st["active"] != 0
                        or kp["blocks_free"] + kp["radix_nodes"]
                        < kp["blocks_total"]):
                    ok = False
            if ok:
                return True
            time.sleep(0.2)
        return False

    def drive(gw, req, out):
        t0 = time.perf_counter()
        toks, ttft, last, gaps_s = [], None, None, []
        try:
            for frame in gw.route_generate_stream(dict(req)):
                evt = _parse_sse(frame)
                if evt is None or evt.get("done"):
                    continue
                if evt.get("tokens"):
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    else:
                        gaps_s.append(now - last)
                    last = now
                    toks.extend(evt["tokens"])
        except Exception as exc:
            out.put((req["request_id"], None, [], [f"error: {exc}"]))
            return
        out.put((req["request_id"], ttft, gaps_s, toks))

    def run_arm(disagg: bool) -> tuple:
        roles = (("prefill", "prefill", "decode", "decode") if disagg
                 else ("both",) * 4)
        workers = make_fleet(roles)
        gw = Gateway(workers, GatewayConfig(
            disagg=disagg, handoff_timeout_s=60.0))
        try:
            # Warm every lane's compile set (prefill chunks, decode
            # ticks, export/import paths) outside the measurement.
            warm = []
            for i in range(4):
                warm.append({"request_id": f"warm-{i}",
                             "prompt_tokens": [3 + i] * (prompt_len // 2),
                             "max_new_tokens": 4})
            wq: _q.Queue = _q.Queue()
            wt = [threading.Thread(target=drive, args=(gw, r, wq))
                  for r in warm]
            for t in wt:
                t.start()
            for t in wt:
                t.join(timeout=300)
            while not wq.empty():
                wq.get()
            # Handoff accounting over the MEASURED window only (the
            # warm streams hand off too).
            ho0 = dict(gw.get_stats().get("handoff", {})) if disagg \
                else {}
            out: _q.Queue = _q.Queue()
            threads = []
            for req, gap in zip(requests, gaps):
                time.sleep(gap)
                t = threading.Thread(target=drive, args=(gw, req, out))
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=600)
            got = {}
            ttfts, itl = [], []
            while not out.empty():
                rid, ttft, gaps_s, toks = out.get()
                got[rid] = toks
                if ttft is not None:
                    ttfts.append(ttft)
                itl.extend(gaps_s)
            ttfts.sort()  # percentile() takes a pre-sorted list
            itl.sort()
            stats = gw.get_stats()
            arm = {
                "disagg": disagg, "streams": len(requests),
                "completed": sum(1 for t in got.values() if t),
                "ttft_ms": {
                    "p50": round(1e3 * (percentile(ttfts, 50) or 0), 1),
                    "p99": round(1e3 * (percentile(ttfts, 99) or 0), 1)},
                "itl_ms": {
                    "p50": round(1e3 * (percentile(itl, 50) or 0), 1),
                    "p99": round(1e3 * (percentile(itl, 99) or 0), 1)},
                "pools_leak_free": leak_free(workers),
            }
            if disagg:
                ho = stats.get("handoff", {})
                arm["handoff"] = {k: ho.get(k, 0) - ho0.get(k, 0)
                                  for k in (
                    "prefill_routed", "handoffs_attempted",
                    "handoffs_spliced", "handoff_fallbacks",
                    "export_refusals", "destination_unavailable",
                    "dispatch_failed")}
                arm["decode_imported_rows"] = sum(
                    (w.generator.stats().get("migration") or {})
                    .get("imported_rows", 0) for w in workers)
                arm["prefill_holds"] = sum(
                    (w.generator.stats().get("handoff") or {})
                    .get("holds", 0) for w in workers)
            else:
                arm["stats_has_handoff_key"] = "handoff" in stats
                arm["health_has_role_key"] = any(
                    "role" in w.get_health() for w in workers)
            return arm, got
        finally:
            gw.stop()
            for w in workers:
                w.stop()

    off, off_tokens = run_arm(False)
    record_partial("disagg_colocated", off)
    on, on_tokens = run_arm(True)
    record_partial("disagg_on", on)

    identical = sum(1 for rid in off_tokens
                    if on_tokens.get(rid) == off_tokens[rid]
                    and off_tokens[rid])

    # Quantized split fleet: the int8+scale chain must ride the hop
    # verbatim — the handed-off stream equals the same quantized
    # fleet's colocated stream (determinism contract: quantized-vs-
    # quantized byte-identity, not bf16 equality).
    def quant_phase() -> dict:
        qreq = {"request_id": "qz-1",
                "prompt_tokens": [rnd.randrange(1, 200)
                                  for _ in range(prompt_len)],
                "max_new_tokens": 12, "temperature": 0.7, "seed": 17}

        def one(roles, disagg):
            workers = []
            for i, role in enumerate(roles):
                cfg = WorkerConfig(
                    node_id=f"q_{i+1}", model=model, role=role,
                    gen_max_batch_size=2, gen_step_chunk=4,
                    gen_prefix_cache_mb=0, gen_kv_block_size=block_size,
                    gen_kv_blocks=colo_blocks, gen_kv_quantize="int8")
                engine = InferenceEngine(spec, params=params,
                                         dtype="float32")
                workers.append(WorkerNode(cfg, engine=engine))
            gw = Gateway(workers, GatewayConfig(
                disagg=disagg, handoff_timeout_s=60.0))
            try:
                out: _q.Queue = _q.Queue()
                drive(gw, qreq, out)
                _rid, _ttft, _gaps, toks = out.get()
                imported = sum(
                    (w.generator.stats().get("migration") or {})
                    .get("imported_rows", 0) for w in workers)
                spliced = (gw.get_stats().get("handoff", {})
                           .get("handoffs_spliced", 0))
                clean = leak_free(workers)
                return toks, imported, spliced, clean
            finally:
                gw.stop()
                for w in workers:
                    w.stop()

        ctoks, _imp, _spl, cclean = one(("both", "both"), False)
        htoks, imported, spliced, hclean = one(("prefill", "decode"),
                                               True)
        return {
            "stream_identical": bool(htoks and htoks == ctoks),
            "imported_rows": imported, "handoffs_spliced": spliced,
            "pools_leak_free": bool(cclean and hclean),
        }

    quant = quant_phase()
    record_partial("disagg_quant", quant)

    results = {
        "model": model, "n_streams": n_streams,
        "prompt_len": prompt_len, "max_new": max_new,
        "lanes": "2 prefill + 2 decode vs 4 colocated mixed-step",
        "colocated": off, "disagg": on,
        "streams_identical_across_arms": identical,
        "ttft_p99_speedup": round(
            off["ttft_ms"]["p99"] / max(on["ttft_ms"]["p99"], 1e-3), 3),
        "itl_p99_speedup": round(
            off["itl_ms"]["p99"] / max(on["itl_ms"]["p99"], 1e-3), 3),
        "quantized_handoff": quant,
    }
    results["checks_passed"] = bool(
        identical == n_streams
        and on["completed"] == n_streams
        and off["completed"] == n_streams
        and on["ttft_ms"]["p99"] < off["ttft_ms"]["p99"]
        and on["itl_ms"]["p99"] < off["itl_ms"]["p99"]
        and on["handoff"]["handoffs_spliced"] == n_streams
        and on["handoff"]["handoff_fallbacks"] == 0
        and on["pools_leak_free"] and off["pools_leak_free"]
        and not off["stats_has_handoff_key"]
        and not off["health_has_role_key"]
        and quant["stream_identical"]
        and quant["imported_rows"] >= 1
        and quant["pools_leak_free"])
    return results


def run_affinity_ab(model: str = "gpt2-small-test", n_requests: int = 48,
                    n_tenants: int = 8, prefix_len: int = 96,
                    suffix_len: int = 8, max_new: int = 8,
                    mean_gap_ms: float = 50.0, block_size: int = 16,
                    lanes: int = 3, slots_per_lane: int = 2,
                    kv_blocks_per_lane: int = 36, max_seq: int = 256,
                    quick: bool = False) -> dict:
    """Prefix-affinity routing A/B (the PR 7 tentpole): a
    shared-system-prompt Poisson workload over >= 3 in-process lanes
    behind the gateway, --prefix-affinity ON vs OFF.

    Workload: ``n_tenants`` distinct system prompts (each
    ``prefix_len`` tokens = full radix blocks), each request = one
    tenant's prefix + a unique suffix, Poisson arrivals, unique
    request_ids. Per-lane pools are sized so ONE lane cannot hold every
    tenant's prefix (the fleet-capacity shape): request_id routing
    scatters every tenant across every lane — each lane churns through
    all ``n_tenants`` prefixes and keeps evicting/re-prefilling them —
    while affinity routing partitions tenants across lanes so each
    lane's radix holds its share resident. Reported per arm:

    - fleet prefill-skip ratio (sum prefix_hit / (hit + prefilled)
      across lanes, warmup excluded) — the bar: ON >= 2x OFF;
    - client-side TTFT p50/p99 through /generate/stream — ON p99 must
      beat OFF (skipped prefill is exactly the TTFT term);
    - per-lane radix_lookups/radix_hits/prefix_hit_tokens (the /stats
      blind-spot fix — affinity effectiveness observable per lane).

    A separate OFFLOAD phase exercises the hierarchical host-RAM tier on
    one lane (tiny device pool + --kv-host-blocks): fillers demote the
    tenant prefix, a re-hit must SWAP IN instead of recomputing
    (swap_in_events > 0, prefill tokens skipped) with the stream
    byte-identical to the pre-demotion run.

    Runs on the CPU mesh (tiny registry model — routing convergence,
    radix hit ratios, and swap-in counters are topology/workload
    properties, not model-size properties); on-chip rerun pending like
    r06-r09."""
    import queue as _q
    import random

    import jax

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.engine import InferenceEngine
    from tpu_engine.runtime.scheduler import ContinuousGenerator
    from tpu_engine.serving.gateway import Gateway
    from tpu_engine.serving.worker import WorkerNode
    from tpu_engine.utils.config import GatewayConfig, WorkerConfig

    _ensure_builtin_models_imported()
    if quick:
        # Smaller run, proportionally tighter pools: 6 tenants x 6 radix
        # blocks must still exceed one lane's capacity or the off arm
        # stops thrashing and the contrast (the thing under test)
        # vanishes into the smaller sample.
        n_requests, n_tenants = 24, 6
        kv_blocks_per_lane = min(kv_blocks_per_lane, 30)
    spec = create_model(model, max_seq=max_seq)
    params = spec.init(jax.random.PRNGKey(0))
    rnd = random.Random(7)
    tenants = [[rnd.randrange(1, 200) for _ in range(prefix_len)]
               for _ in range(n_tenants)]
    requests = []
    for i in range(n_requests):
        prompt = (tenants[i % n_tenants]
                  + [rnd.randrange(1, 200) for _ in range(suffix_len)])
        requests.append({"request_id": f"aff-{i}", "prompt_tokens": prompt,
                         "max_new_tokens": max_new})
    gaps = [rnd.expovariate(1000.0 / mean_gap_ms) / 1000.0
            for _ in range(n_requests)]

    def make_fleet():
        workers = []
        for i in range(lanes):
            cfg = WorkerConfig(
                node_id=f"lane_{i+1}", model=model,
                gen_max_batch_size=slots_per_lane, gen_step_chunk=8,
                gen_prefix_cache_mb=0, gen_kv_block_size=block_size,
                gen_kv_blocks=kv_blocks_per_lane)
            engine = InferenceEngine(spec, params=params, dtype="float32")
            workers.append(WorkerNode(cfg, engine=engine))
        return workers

    def fleet_kv(workers):
        per_lane, agg = {}, {"prefix_hit_tokens": 0, "prefilled_tokens": 0,
                             "radix_lookups": 0, "radix_hits": 0}
        for w in workers:
            pool = w.generator.stats()["kv_pool"]
            per_lane[w.node_id] = {k: pool[k] for k in agg}
            for k in agg:
                agg[k] += pool[k]
        return per_lane, agg

    from tpu_engine.serving.gateway import _parse_sse
    from tpu_engine.utils.tracing import percentile

    def first_token_ttft(gw, req, out):
        t0 = time.perf_counter()
        toks = []
        ttft = None
        for frame in gw.route_generate_stream(dict(req)):
            evt = _parse_sse(frame)
            if evt is None or evt.get("done"):
                continue
            if ttft is None and evt.get("tokens"):
                ttft = time.perf_counter() - t0
            toks.extend(evt.get("tokens", ()))
        out.put((req["request_id"], ttft, toks))

    def run_arm(affinity: bool) -> dict:
        workers = make_fleet()
        gw = Gateway(workers, GatewayConfig(
            prefix_affinity=affinity, affinity_block_size=block_size))
        try:
            # Warm EVERY lane's compile set on both the miss path (full
            # bucket prefill) and the radix-hit resumed-window path, with
            # a warm-only prefix, then snapshot the counters so the
            # measured ratios exclude warmup.
            warm_prefix = [rnd.randrange(200, 255)
                           for _ in range(prefix_len)]
            for w in workers:
                for s in ((1, 2, 3, 4), (9, 8, 7)):
                    w.handle_generate({
                        "request_id": f"warm-{w.node_id}-{len(s)}",
                        "prompt_tokens": warm_prefix + list(s),
                        "max_new_tokens": 2})
            _, base = fleet_kv(workers)

            out: "_q.Queue" = _q.Queue()
            threads = []
            t0 = time.perf_counter()
            for req, gap in zip(requests, gaps):
                time.sleep(gap)
                th = threading.Thread(target=first_token_ttft,
                                      args=(gw, req, out), daemon=True)
                th.start()
                threads.append(th)
            for th in threads:
                th.join(timeout=600)
            wall = time.perf_counter() - t0
            got = {}
            ttfts = []
            while not out.empty():
                rid, ttft, toks = out.get()
                got[rid] = toks
                if ttft is not None:
                    ttfts.append(ttft)
            ttfts.sort()  # percentile() takes a pre-sorted list
            per_lane, agg = fleet_kv(workers)
            hit = agg["prefix_hit_tokens"] - base["prefix_hit_tokens"]
            filled = agg["prefilled_tokens"] - base["prefilled_tokens"]
            arm = {
                "affinity": affinity, "requests": len(requests),
                "completed": sum(1 for t in got.values() if t),
                "wall_s": round(wall, 3),
                "fleet_prefill_skip_frac": round(
                    hit / (hit + filled), 4) if hit + filled else 0.0,
                "prefix_hit_tokens": hit, "prefilled_tokens": filled,
                "ttft_p50_ms": round(1e3 * (percentile(ttfts, 50) or 0), 2),
                "ttft_p99_ms": round(1e3 * (percentile(ttfts, 99) or 0), 2),
                "per_lane_kv": per_lane,
            }
            st = gw.get_stats()
            if affinity:
                arm["affinity_stats"] = st["affinity"]
            else:
                arm["affinity_block_absent"] = "affinity" not in st
            return arm, got
        finally:
            gw.stop()
            for w in workers:
                w.stop()

    results = {"model": model, "lanes": lanes, "n_requests": n_requests,
               "n_tenants": n_tenants, "prefix_len": prefix_len,
               "block_size": block_size,
               "kv_blocks_per_lane": kv_blocks_per_lane}
    off, off_streams = run_arm(False)
    record_partial("affinity_off", off)
    on, on_streams = run_arm(True)
    record_partial("affinity_on", on)
    results["affinity_off"], results["affinity_on"] = off, on
    results["skip_gain"] = round(
        on["fleet_prefill_skip_frac"]
        / max(1e-9, off["fleet_prefill_skip_frac"]), 2)
    results["streams_identical_on_vs_off"] = all(
        on_streams.get(r) == off_streams.get(r) for r in on_streams)

    # -- offload phase: host tier swap-in instead of recompute ---------------
    g = ContinuousGenerator(spec, params=params, dtype="float32",
                            n_slots=slots_per_lane, step_chunk=8,
                            max_seq=max_seq, kv_block_size=block_size,
                            kv_blocks=20, kv_host_blocks=16)
    try:
        tprompt = tenants[0] + [3, 1, 4]
        want = g.generate([tprompt], max_new_tokens=max_new)[0]
        for _ in range(4):  # fillers demote the tenant prefix
            g.generate([[rnd.randrange(1, 200) for _ in range(72)]],
                       max_new_tokens=2)
        mid = g.stats()["kv_pool"]
        got = g.generate([tprompt], max_new_tokens=max_new)[0]
        pool = g.stats()["kv_pool"]
        results["offload"] = {
            "demotions": pool["host"]["demotions"],
            "swap_ins": pool["host"]["swap_ins"],
            "swap_in_events": pool["host"]["swap_in_events"],
            "swapped_in_tokens": pool["host"]["swapped_in_tokens"],
            "prefill_tokens_skipped_on_rehit":
                pool["prefix_hit_tokens"] - mid["prefix_hit_tokens"],
            "stream_identical_after_swap_in": got == want,
        }
    finally:
        g.stop()
    record_partial("affinity_offload", results["offload"])

    results["checks_passed"] = bool(
        on["completed"] == n_requests and off["completed"] == n_requests
        and results["streams_identical_on_vs_off"]
        and on["fleet_prefill_skip_frac"]
        >= 2.0 * off["fleet_prefill_skip_frac"]
        and on["ttft_p99_ms"] < off["ttft_p99_ms"]
        and off["affinity_block_absent"]
        and results["offload"]["swap_in_events"] > 0
        and results["offload"]["prefill_tokens_skipped_on_rehit"] > 0
        and results["offload"]["stream_identical_after_swap_in"])
    return results


def run_fleet_prefix_ab(model: str = "gpt2-small-test",
                        n_tenants: int = 6, rounds: int = 4,
                        prefix_len: int = 96, suffix_len: int = 8,
                        max_new: int = 8, block_size: int = 16,
                        lanes: int = 3, slots_per_lane: int = 2,
                        kv_blocks_per_lane: int = 64, max_seq: int = 256,
                        quick: bool = False) -> dict:
    """Fleet-wide KV prefix tier A/B (the PR 18 tentpole): gateway radix
    directory + peer block fetch vs plain ring routing, on an
    AFFINITY-DEFEATING workload — prefix affinity stays OFF and every
    round's request_ids are chosen so the ring lands each tenant's
    shared prefix on a lane that has never seen it. That is exactly the
    shape affinity routing cannot fix (unique ids scatter by design)
    and the directory+fetch tier is built for.

    Workload: ``n_tenants`` shared prefixes (each ``prefix_len`` tokens
    = full radix blocks), ``rounds`` rounds; round 1 establishes each
    tenant's owner lane, the middle rounds deliberately ring-route to a
    lane that has never held the tenant (the cold repeats the fetch
    tier converts), and the FINAL round revisits a warm lane — the same
    local radix hit in both arms, so the off arm's baseline is the
    honest "local hits only" number rather than a degenerate zero.
    Per-lane pools comfortably hold every tenant (no eviction pressure
    — the contrast under test is re-prefill vs peer fetch, not
    capacity). Reported per arm:

    - fleet prefill-skip ratio: (local prefix_hit_tokens +
      prefill_tokens_skipped_remote) / (those + prefilled_tokens),
      warmup excluded — the bar: FETCH >= 2x OFF;
    - client TTFT p50/p99 through /generate/stream (sequential issue —
      ownership must be established before the next round probes it);
    - fetch-arm: gateway prefix_directory stats + per-lane prefix_fetch
      counters (attempted == spliced: no rung ever fires on a healthy
      fleet); off-arm: /stats carries NO prefix_directory block and no
      lane grew a prefix_fetch family (defaults-off wire compat).

    Streams must be byte-identical across arms. Runs on the CPU mesh
    (directory convergence and splice accounting are topology/workload
    properties, not model-size properties); on-chip rerun pending like
    r06-r09."""
    import random

    import jax

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.engine import InferenceEngine
    from tpu_engine.serving.gateway import Gateway, _parse_sse
    from tpu_engine.serving.worker import WorkerNode
    from tpu_engine.utils.config import GatewayConfig, WorkerConfig
    from tpu_engine.utils.tracing import percentile

    _ensure_builtin_models_imported()
    if quick:
        n_tenants = 3
    spec = create_model(model, max_seq=max_seq)
    params = spec.init(jax.random.PRNGKey(0))
    rnd = random.Random(18)
    tenants = [[rnd.randrange(1, 200) for _ in range(prefix_len)]
               for _ in range(n_tenants)]
    suffixes = [[rnd.randrange(1, 200) for _ in range(suffix_len)]
                for _ in range(n_tenants * rounds)]
    n_requests = n_tenants * rounds

    def make_fleet(fetch: bool):
        workers = []
        for i in range(lanes):
            cfg = WorkerConfig(
                node_id=f"lane_{i+1}", model=model,
                gen_max_batch_size=slots_per_lane, gen_step_chunk=8,
                gen_prefix_cache_mb=0, gen_kv_block_size=block_size,
                gen_kv_blocks=kv_blocks_per_lane,
                gen_prefix_fetch=fetch)
            engine = InferenceEngine(spec, params=params, dtype="float32")
            workers.append(WorkerNode(cfg, engine=engine))
        if fetch:
            by_name = {w.node_id: w for w in workers}

            def transport(hint, payload):
                return by_name[hint["lane"]].handle_export_prefix(payload)
            for w in workers:
                w.set_prefix_fetch_transport(transport)
        return workers

    def fleet_counters(workers):
        agg = {"prefix_hit_tokens": 0, "prefilled_tokens": 0,
               "remote_skipped_tokens": 0, "fetch_attempted": 0,
               "fetch_spliced": 0, "fetch_blocks": 0}
        per_lane = {}
        for w in workers:
            st = w.generator.stats()
            pool = st["kv_pool"]
            pf = st.get("prefix_fetch") or {}
            row = {"prefix_hit_tokens": pool["prefix_hit_tokens"],
                   "prefilled_tokens": pool["prefilled_tokens"],
                   "remote_skipped_tokens":
                       pf.get("prefill_tokens_skipped_remote", 0),
                   "fetch_attempted": pf.get("attempted", 0),
                   "fetch_spliced": pf.get("spliced", 0),
                   "fetch_blocks": pf.get("blocks_spliced", 0)}
            per_lane[w.node_id] = row
            for k in agg:
                agg[k] += row[k]
        return per_lane, agg

    def stream_one(gw, req):
        t0 = time.perf_counter()
        toks, ttft = [], None
        for frame in gw.route_generate_stream(dict(req)):
            evt = _parse_sse(frame)
            if evt is None or evt.get("done"):
                continue
            if ttft is None and evt.get("tokens"):
                ttft = time.perf_counter() - t0
            toks.extend(evt.get("tokens", ()))
        return toks, ttft

    def pick_rid(gw, holders, tag, warm):
        """A request_id whose ring primary is IN ``holders`` (warm
        revisit) or NOT in it (the affinity-defeating cold step). Same
        ring membership both arms, so the chosen ids — and thus the
        routing — are identical across arms."""
        for i in range(4000):
            rid = f"{tag}-{i}"
            if (gw._ring.get_node(rid) in holders) == warm:
                return rid
        return f"{tag}-0"

    def run_arm(fetch: bool) -> tuple:
        workers = make_fleet(fetch)
        gw = Gateway(workers, GatewayConfig(prefix_directory=fetch))
        try:
            # Warm every lane's compile set on the miss path AND the
            # block-aligned resumed-window path (the same windows a
            # splice resumes into), then snapshot counters so measured
            # ratios exclude warmup.
            warm_prefix = [rnd.randrange(200, 255)
                           for _ in range(prefix_len)]
            for w in workers:
                for s in ((1, 2, 3, 4), (9, 8, 7)):
                    w.handle_generate({
                        "request_id": f"warm-{w.node_id}-{len(s)}",
                        "prompt_tokens": warm_prefix + list(s),
                        "max_new_tokens": 2})
            _, base = fleet_counters(workers)

            streams = {}
            ttfts = []
            served_by = {}  # tenant -> lanes that have its prefix
            wall0 = time.perf_counter()
            for r in range(rounds):
                for t in range(n_tenants):
                    # Middle rounds steer AWAY from every lane that
                    # already holds this tenant's blocks (each repeat a
                    # cold lane, the ring at its least favorable); the
                    # last round revisits a warm one (both arms hit
                    # locally — the honest shared baseline).
                    rid = pick_rid(gw, served_by.get(t, set()),
                                   f"fp-t{t}-r{r}", warm=r == rounds - 1)
                    prompt = tenants[t] + suffixes[r * n_tenants + t]
                    toks, ttft = stream_one(
                        gw, {"request_id": rid, "prompt_tokens": prompt,
                             "max_new_tokens": max_new})
                    streams[(t, r)] = toks
                    if ttft is not None:
                        ttfts.append(ttft)
                    served_by.setdefault(t, set()).add(
                        gw._ring.get_node(rid))
            wall = time.perf_counter() - wall0
            ttfts.sort()
            per_lane, agg = fleet_counters(workers)
            skip = {k: agg[k] - base[k] for k in agg}
            gained = (skip["prefix_hit_tokens"]
                      + skip["remote_skipped_tokens"])
            filled = skip["prefilled_tokens"]
            arm = {
                "prefix_fetch": fetch, "requests": n_requests,
                "completed": sum(1 for s in streams.values() if s),
                "wall_s": round(wall, 3),
                "fleet_prefill_skip_frac": round(
                    gained / (gained + filled), 4) if gained + filled
                    else 0.0,
                "local_hit_tokens": skip["prefix_hit_tokens"],
                "remote_skipped_tokens": skip["remote_skipped_tokens"],
                "prefilled_tokens": filled,
                "fetch_attempted": skip["fetch_attempted"],
                "fetch_spliced": skip["fetch_spliced"],
                "fetch_blocks_spliced": skip["fetch_blocks"],
                "ttft_p50_ms": round(1e3 * (percentile(ttfts, 50) or 0), 2),
                "ttft_p99_ms": round(1e3 * (percentile(ttfts, 99) or 0), 2),
                "per_lane": per_lane,
            }
            st = gw.get_stats()
            if fetch:
                arm["prefix_directory"] = st["prefix_directory"]
            else:
                arm["directory_block_absent"] = (
                    "prefix_directory" not in st)
                arm["fetch_stats_absent"] = all(
                    "prefix_fetch" not in w.generator.stats()
                    for w in workers)
            return arm, streams
        finally:
            gw.stop()
            for w in workers:
                w.stop()

    results = {"model": model, "lanes": lanes, "n_requests": n_requests,
               "n_tenants": n_tenants, "rounds": rounds,
               "prefix_len": prefix_len, "block_size": block_size,
               "kv_blocks_per_lane": kv_blocks_per_lane}
    off, off_streams = run_arm(False)
    record_partial("fleet_prefix_off", off)
    on, on_streams = run_arm(True)
    record_partial("fleet_prefix_on", on)
    results["fetch_off"], results["fetch_on"] = off, on
    results["skip_gain"] = round(
        on["fleet_prefill_skip_frac"]
        / max(1e-4, off["fleet_prefill_skip_frac"]), 2)
    results["streams_identical_on_vs_off"] = all(
        on_streams.get(k) == off_streams.get(k) for k in on_streams)
    results["checks_passed"] = bool(
        on["completed"] == n_requests and off["completed"] == n_requests
        and results["streams_identical_on_vs_off"]
        and on["fleet_prefill_skip_frac"]
        >= 2.0 * max(off["fleet_prefill_skip_frac"], 1e-9)
        and on["fetch_spliced"] > 0
        and on["fetch_attempted"] == on["fetch_spliced"]
        and on["prefix_directory"]["hints_attached"] > 0
        and off["directory_block_absent"]
        and off["fetch_stats_absent"])
    return results


def run_overload_ab(model: str = "gpt2-small-test", n_requests: int = 60,
                    max_new: int = 16, lanes: int = 3,
                    slots_per_lane: int = 2, block_size: int = 16,
                    max_seq: int = 128, quick: bool = False) -> dict:
    """Adaptive overload control A/B (the PR 9 tentpole): mixed-priority
    Poisson load at ~2x saturation over >= 3 in-process paged mixed-step
    lanes behind the gateway — overload control ON (priority-tiered
    gateway+worker admission, staged brownout, load-derived Retry-After)
    vs OFF (PR 1 behavior: everything admits, deadlines alone decide).

    Both arms carry identical per-request deadlines; the headline is
    GOODPUT — tokens of requests that completed within their deadline,
    per second of wall — split by tier. The off arm melts every tier
    equally (queues grow past the deadline for everyone); the on arm
    sheds background/batch early and keeps interactive inside its
    deadline. Bar: on-arm INTERACTIVE goodput >= 1.5x the off arm's,
    and a below-saturation stream is byte-identical across arms (the
    control plane must not touch stream content).

    Runs on the CPU mesh (tiny registry model — admission ordering,
    ladder behavior, and goodput shape are control-plane properties,
    not model-size properties); on-chip rerun pending like r06-r10."""
    import queue as _q
    import random

    import jax

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.engine import InferenceEngine
    from tpu_engine.serving.gateway import Gateway, _parse_sse
    from tpu_engine.serving.worker import WorkerNode
    from tpu_engine.utils.config import GatewayConfig, WorkerConfig
    from tpu_engine.utils.deadline import ShedError
    from tpu_engine.utils.tracing import percentile

    _ensure_builtin_models_imported()
    if quick:
        n_requests = 42
    spec = create_model(model, max_seq=max_seq)
    params = spec.init(jax.random.PRNGKey(0))
    rnd = random.Random(11)
    tiers = ["interactive", "batch", "background"]
    requests = []
    for i in range(n_requests):
        requests.append({
            "request_id": f"ov-{i}",
            "prompt_tokens": [rnd.randrange(1, 200) for _ in range(12)],
            "max_new_tokens": max_new,
            "priority": tiers[i % 3],
        })

    def make_fleet(overload: bool, slo_ms: float = 0.0):
        # The OFF arm is the PR 1 default: unbounded admission, the
        # deadline machinery alone decides — exactly the uncontrolled
        # baseline the tentpole replaces. The ON arm bounds depth,
        # tiers admission, and runs the brownout ladder. slo_ms > 0
        # additionally declares TTFT/completion objectives derived
        # from the arm's deadline, so the artifact carries the
        # error-budget burn the run actually produced.
        workers = []
        for i in range(lanes):
            cfg = WorkerConfig(
                node_id=f"lane_{i+1}", model=model,
                gen_max_batch_size=slots_per_lane, gen_step_chunk=8,
                gen_prefix_cache_mb=0, gen_kv_block_size=block_size,
                gen_kv_blocks=24, gen_mixed_step=True,
                gen_mixed_token_budget=16,
                # ON arm: admitted == decodable now (depth = decode
                # slots) — a queued-but-doomed admission is exactly the
                # goodput leak the control plane exists to close.
                max_queue_depth=slots_per_lane if overload else 0,
                priority_admission=overload, brownout=overload,
                brownout_interval_s=0.15)
            engine = InferenceEngine(spec, params=params, dtype="float32")
            workers.append(WorkerNode(cfg, engine=engine))
        gw = Gateway(workers, GatewayConfig(
            overload_control=overload,
            overload_max_inflight=(2 * lanes * slots_per_lane
                                   if overload else 0),
            slo_ttft_p99_ms=(slo_ms / 2 if slo_ms else 0.0),
            slo_completion_p99_ms=(slo_ms if slo_ms else 0.0)))
        return workers, gw

    def consume(gw, req, deadline_ms, out):
        t0 = time.perf_counter()
        toks, ttft, ok, shed = [], None, False, False
        try:
            for frame in gw.route_generate_stream(
                    dict(req, deadline_ms=deadline_ms)):
                evt = _parse_sse(frame)
                if evt is None:
                    continue
                if evt.get("done"):
                    ok = "error" not in evt
                    break
                if ttft is None and evt.get("tokens"):
                    ttft = time.perf_counter() - t0
                toks.extend(evt.get("tokens", ()))
        except ShedError:
            shed = True
        except Exception:
            pass
        out.put((req["request_id"], req["priority"], ok, shed, ttft,
                 len(toks), (time.perf_counter() - t0) * 1e3))

    def run_arm(overload: bool, rate_hz: float, deadline_ms: float):
        # SLO accounting rides the measured arms only (the ON arm's
        # objectives track its deadline); calibration and the identity
        # probe stay flag-free.
        workers, gw = make_fleet(overload,
                                 slo_ms=deadline_ms if overload else 0.0)
        try:
            for w in workers:  # warm the compile set off the clock
                w.handle_generate({"request_id": f"warm-{w.node_id}",
                                   "prompt_tokens": [1, 2, 3, 4],
                                   "max_new_tokens": 2})
            out: "_q.Queue" = _q.Queue()
            gaps = [rnd.expovariate(rate_hz) for _ in requests]
            threads = []
            t0 = time.perf_counter()
            for req, gap in zip(requests, gaps):
                time.sleep(gap)
                th = threading.Thread(target=consume,
                                      args=(gw, req, deadline_ms, out),
                                      daemon=True)
                th.start()
                threads.append(th)
            for th in threads:
                th.join(timeout=600)
            wall = time.perf_counter() - t0
            by_tier = {t: {"offered": 0, "good": 0, "shed": 0,
                           "missed": 0, "good_tokens": 0, "ttfts": []}
                       for t in tiers}
            while not out.empty():
                rid, tier, ok, shed, ttft, n_toks, lat_ms = out.get()
                d = by_tier[tier]
                d["offered"] += 1
                if ok and lat_ms <= deadline_ms:
                    d["good"] += 1
                    d["good_tokens"] += n_toks
                    if ttft is not None:
                        d["ttfts"].append(ttft)
                elif shed:
                    d["shed"] += 1
                else:
                    d["missed"] += 1
            arm = {"overload_control": overload, "wall_s": round(wall, 3),
                   "by_tier": {}}
            for t in tiers:
                d = by_tier[t]
                d["ttfts"].sort()
                arm["by_tier"][t] = {
                    "offered": d["offered"], "good": d["good"],
                    "shed": d["shed"], "missed": d["missed"],
                    "goodput_tokens_per_s": round(
                        d["good_tokens"] / wall, 3),
                    "ttft_p99_ms": round(
                        1e3 * (percentile(d["ttfts"], 99) or 0), 2),
                }
            st = gw.get_stats()
            if overload:
                arm["gateway_overload"] = st.get("overload")
                # SLO burn-rate block rides the same armed stats
                # snapshot: budget burn per objective for the arm.
                arm["slo"] = st.get("slo")
                arm["brownout"] = {
                    w.node_id: w.get_health().get("brownout")
                    for w in workers}
            else:
                arm["overload_block_absent"] = "overload" not in st
            return arm
        finally:
            gw.stop()
            for w in workers:
                w.stop()

    # Calibration: a full-concurrency burst on a warm uncontrolled
    # fleet measures what the HOST actually sustains (sequential singles
    # understate concurrent service on a shared-CPU mesh). Capacity =
    # completed/wall; the deadline is twice the burst's mean latency —
    # an at-capacity request makes it comfortably, one queued behind 2x
    # overload does not.
    workers, gw = make_fleet(False)
    try:
        for w in workers:
            w.handle_generate({"request_id": f"cal-warm-{w.node_id}",
                               "prompt_tokens": [1, 2, 3, 4],
                               "max_new_tokens": 2})
        n_cal = 2 * lanes * slots_per_lane
        lats: list = []
        lat_lock = threading.Lock()

        def cal_one(i):
            t1 = time.perf_counter()
            gw.route_generate({"request_id": f"cal-{i}",
                               "prompt_tokens": [5, 9, 3, 7],
                               "max_new_tokens": max_new})
            with lat_lock:
                lats.append(time.perf_counter() - t1)

        t0 = time.perf_counter()
        cal_threads = [threading.Thread(target=cal_one, args=(i,),
                                        daemon=True)
                       for i in range(n_cal)]
        for th in cal_threads:
            th.start()
        for th in cal_threads:
            th.join(timeout=600)
        cal_wall = time.perf_counter() - t0
    finally:
        gw.stop()
        for w in workers:
            w.stop()
    svc_s = sum(lats) / max(1, len(lats))
    capacity_hz = len(lats) / max(cal_wall, 1e-3)
    rate_hz = 2.0 * capacity_hz
    deadline_ms = max(400.0, 2.5 * svc_s * 1e3)

    # Below-saturation identity: one idle-fleet stream per arm must be
    # byte-identical (overload control must never touch stream bytes).
    ident_req = {"request_id": "ident", "prompt_tokens": [5, 9, 3, 7],
                 "max_new_tokens": max_new, "priority": "background"}
    ident = {}
    for overload in (False, True):
        workers, gw = make_fleet(overload)
        try:
            frames = list(gw.route_generate_stream(dict(ident_req)))
            toks = []
            for f in frames:
                evt = _parse_sse(f)
                if evt and not evt.get("done"):
                    toks.extend(evt.get("tokens", ()))
            ident[overload] = toks
        finally:
            gw.stop()
            for w in workers:
                w.stop()

    results = {"model": model, "lanes": lanes,
               "slots_per_lane": slots_per_lane,
               "n_requests": n_requests, "max_new": max_new,
               "calibrated_service_s": round(svc_s, 3),
               "offered_rate_hz": round(rate_hz, 3),
               "estimated_capacity_hz": round(capacity_hz, 3),
               "deadline_ms": round(deadline_ms, 1),
               "streams_identical_below_saturation":
                   bool(ident[False]) and ident[False] == ident[True]}
    off = run_arm(False, rate_hz, deadline_ms)
    record_partial("overload_off", off)
    on = run_arm(True, rate_hz, deadline_ms)
    record_partial("overload_on", on)
    results["overload_off"], results["overload_on"] = off, on
    on_hi = on["by_tier"]["interactive"]["goodput_tokens_per_s"]
    off_hi = off["by_tier"]["interactive"]["goodput_tokens_per_s"]
    results["interactive_goodput_gain"] = round(
        on_hi / max(1e-9, off_hi), 2) if off_hi or on_hi else None
    results["checks_passed"] = bool(
        results["streams_identical_below_saturation"]
        and off["overload_block_absent"]
        and on_hi >= 1.5 * off_hi
        and on_hi > 0)
    return results


def run_elastic_ab(model: str = "gpt2-chaos-test",
                   max_lanes: int = 4, quick: bool = False) -> dict:
    """Elastic fleet A/B (DESIGN.md "Elastic fleet"): the SAME diurnal
    trace — a Poisson burst, then a sparse trough — served by a static
    ``max_lanes`` fleet vs the ``--autoscale`` closed loop starting from
    one lane (in-process lanes; InProcessLaneProvider spawns and retires
    scheduler instances live, retirements drain through the PR 11
    stream-migration ladder).

    The headline is LANE-SECONDS — the integral of live lane count over
    the run, the capacity bill a fleet actually pays — at EQUAL
    completion: both arms must finish every stream, and every stream's
    tokens must be identical across arms (growth, drain, and migration
    may never touch stream content). Bar: the elastic arm completes the
    trace on provably fewer lane-seconds than the static arm; it must
    also have actually ridden the loop (scaled up to >= 3 lanes inside
    the burst, back down to 1 in the trough) rather than winning by
    standing still, with fleet counters == fleet marker spans.

    Uses gpt2-chaos-test (not gpt2-small-test): the loop steers by slot
    occupancy, and the tiny model drains bursts faster than a 4 Hz
    control loop can sample them. Runs on the CPU mesh (control-plane
    property, not a model-size property); on-chip rerun pending like
    r06-r10."""
    import random
    import threading

    import jax

    from tpu_engine.models.registry import (_ensure_builtin_models_imported,
                                            create_model)
    from tpu_engine.runtime.engine import InferenceEngine
    from tpu_engine.serving.autoscaler import InProcessLaneProvider
    from tpu_engine.serving.gateway import Gateway, _parse_sse
    from tpu_engine.serving.resilience import FleetCounters
    from tpu_engine.serving.worker import WorkerNode
    from tpu_engine.utils.config import GatewayConfig, WorkerConfig

    _ensure_builtin_models_imported()
    spec = create_model(model, max_seq=128)
    params = spec.init(jax.random.PRNGKey(0))
    n_burst = 12 if quick else 24
    n_trough = 4 if quick else 6
    requests = []
    for k in range(n_burst + n_trough):
        params_k = {}
        if k % 3 == 1:
            params_k = {"temperature": 0.9, "seed": 400 + k}
        requests.append({
            "request_id": f"eb_{k}",
            "prompt_tokens": [(k * 5 + j) % 90 + 1
                              for j in range(5 + k % 3)],
            "max_new_tokens": 48 if k < n_burst else 16,
            **params_k})

    def make_lane(name: str) -> WorkerNode:
        cfg = WorkerConfig(node_id=name, model=model,
                           gen_scheduler="continuous",
                           gen_max_batch_size=8, gen_step_chunk=2,
                           gen_kv_block_size=16, gen_kv_blocks=48,
                           gen_prefill_chunk=16, gen_prefix_cache_mb=0)
        engine = InferenceEngine(spec, params=params, dtype="float32")
        return WorkerNode(cfg, engine=engine)

    def run_arm(elastic: bool) -> dict:
        lanes = ([make_lane("el_seed")] if elastic
                 else [make_lane(f"st_{i}") for i in range(max_lanes)])
        retired: list = []
        if elastic:
            gw = Gateway(lanes, GatewayConfig(
                autoscale=True, autoscale_interval_s=0.25,
                autoscale_min_lanes=1, autoscale_max_lanes=max_lanes,
                autoscale_up_pressure=0.30,
                autoscale_down_pressure=0.20,
                autoscale_cooldown_s=0.5,
                autoscale_spawn_timeout_s=60.0,
                migrate_streams=True, failover_streams=True))
            provider = InProcessLaneProvider(
                lambda idx: make_lane(f"el_{idx}"),
                on_retire=retired.append)
            gw.engage_autoscaler(provider=provider)
        else:
            gw = Gateway(lanes, GatewayConfig())

        results: dict = {}
        lock = threading.Lock()
        samples: list = []
        stop_sampling = threading.Event()

        def sampler():
            while not stop_sampling.wait(0.2):
                samples.append((time.monotonic(),
                                len(gw.worker_names())))

        def consume(req):
            toks, final = [], None
            try:
                for frame in gw.route_generate_stream(dict(req)):
                    evt = _parse_sse(frame)
                    if evt is None:
                        continue
                    if evt.get("done"):
                        final = evt
                        break
                    if "tokens" in evt:
                        toks.extend(evt["tokens"])
            except Exception as exc:
                final = {"harness_exception": str(exc)}
            with lock:
                results[req["request_id"]] = (toks, final)

        t0 = time.monotonic()
        samples.append((t0, len(gw.worker_names())))
        sam = threading.Thread(target=sampler, daemon=True)
        sam.start()
        rng = random.Random(23)
        threads = []
        for i, req in enumerate(requests):
            t = threading.Thread(target=consume, args=(req,),
                                 daemon=True)
            t.start()
            threads.append(t)
            if i == n_burst - 1:
                time.sleep(6.0)         # the trough opens
            elif i < n_burst:
                time.sleep(rng.expovariate(8.0))
            else:
                time.sleep(rng.expovariate(0.3))
        for t in threads:
            t.join(timeout=600)
        if elastic:
            # Let the loop settle back to min-lanes — those lane-seconds
            # stay on the elastic arm's bill (the sampler keeps running).
            settle = time.monotonic() + 20.0
            while (len(gw.worker_names()) > 1
                   and time.monotonic() < settle):
                time.sleep(0.2)
        t1 = time.monotonic()
        stop_sampling.set()
        sam.join(timeout=5)
        samples.append((t1, len(gw.worker_names())))
        lane_seconds = sum((samples[i + 1][0] - samples[i][0])
                           * samples[i][1]
                           for i in range(len(samples) - 1))
        lane_counts = [n for _, n in samples]
        fl = dict(gw.get_stats().get("fleet", {}))
        spans = [s for s in gw.tracer.snapshot() if s["op"] == "fleet"]
        counters_match = (len(spans) == sum(
            fl.get(f, 0) for f in FleetCounters.SPAN_FIELDS))
        completed = sum(1 for toks, final in results.values()
                        if final and final.get("done")
                        and "error" not in final)
        tokens = {rid: final.get("tokens") if final else None
                  for rid, (toks, final) in results.items()}
        gw.stop()
        for w in lanes + retired:
            try:
                w.stop()
            except Exception:
                pass
        return {"wall_s": round(t1 - t0, 2),
                "lane_seconds": round(lane_seconds, 2),
                "completed": completed,
                "peak_lanes": max(lane_counts),
                "final_lanes": lane_counts[-1],
                "fleet": fl, "counters_match_spans": counters_match,
                "tokens": tokens}

    log(f"elastic-ab: static arm ({max_lanes} lanes, "
        f"{len(requests)} streams)")
    static = run_arm(elastic=False)
    record_partial("elastic_ab_static", {
        k: v for k, v in static.items() if k != "tokens"})
    log(f"elastic-ab: elastic arm (1..{max_lanes} lanes, closed loop)")
    elastic = run_arm(elastic=True)
    record_partial("elastic_ab_elastic", {
        k: v for k, v in elastic.items() if k != "tokens"})

    n = len(requests)
    identical = sum(
        1 for rid in static["tokens"]
        if static["tokens"][rid] is not None
        and static["tokens"][rid] == elastic["tokens"].get(rid))
    checks = {
        "static_completed_all": static["completed"] == n,
        "elastic_completed_all": elastic["completed"] == n,
        "tokens_identical_across_arms": identical == n,
        "elastic_fewer_lane_seconds":
            elastic["lane_seconds"] < static["lane_seconds"],
        "elastic_scaled_up": elastic["peak_lanes"] >= 3,
        "elastic_scaled_back_down": elastic["final_lanes"] == 1,
        "fleet_counters_match_spans": elastic["counters_match_spans"],
    }
    out = {
        "model": model, "streams": n,
        "static": {k: v for k, v in static.items() if k != "tokens"},
        "elastic": {k: v for k, v in elastic.items() if k != "tokens"},
        "identical_across_arms": identical,
        "lane_seconds_saved": round(
            static["lane_seconds"] - elastic["lane_seconds"], 2),
        "lane_seconds_ratio": round(
            elastic["lane_seconds"] / max(static["lane_seconds"], 1e-9),
            4),
        "checks": checks,
        "checks_passed": all(checks.values()),
    }
    return out


def probe_device(timeout_s: float = 240.0, attempts: int = 3,
                 retry_sleep_s: float = 90.0) -> None:
    """Device-liveness preflight in a SUBPROCESS. The axon tunnel, when
    wedged (observed after compile-OOM storms), hangs device work in
    every new process — an in-process hang would leave the driver with NO
    bench artifact at all. Raises on a dead/hung device.

    The probe runs a tiny matmul, not just `jax.devices()` — a wedged
    tunnel has been observed to still enumerate the device while hanging
    the first executed op. Wedges are sometimes transient (the remote side
    drains a stuck compile), so the probe retries with a pause before
    giving up on the round's artifact.

    A hung child can sit in uninterruptible sleep and survive SIGKILL, so
    pipes are abandoned on timeout instead of drained (subprocess.run's
    post-kill communicate() has no timeout and would hang right here)."""
    code = ("import os, jax, jax.numpy as jnp\n"
            "p = os.environ.get('TPU_ENGINE_PLATFORM')\n"
            "jax.config.update('jax_platforms', p) if p else None\n"
            "x = jnp.ones((128, 128), jnp.bfloat16)\n"
            "jax.block_until_ready(x @ x)\n"
            "print(jax.devices()[0].device_kind)\n")
    last = None
    for attempt in range(1, attempts + 1):
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                                text=True)
        try:
            out, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            for pipe in (proc.stdout, proc.stderr):
                if pipe is not None:
                    pipe.close()
            last = RuntimeError(
                f"device probe hung >{timeout_s:.0f}s (tunnel wedged?)")
        else:
            if proc.returncode == 0:
                log(f"device probe OK: {out.strip()}")
                return
            # Nonzero exits split two ways: device-contention errors (the
            # previous round's server still releasing the chip) are
            # transient and retry; anything else (bad install/platform
            # env) is deterministic and fails fast so the driver still
            # gets its artifact.
            transient = any(sig in err for sig in (
                "already in use", "UNAVAILABLE", "DEADLINE_EXCEEDED",
                "RESOURCE_EXHAUSTED"))
            if not transient:
                raise RuntimeError(f"device probe failed: {err[-300:]}")
            last = RuntimeError(f"device busy: {err[-300:]}")
        log(f"device probe attempt {attempt}/{attempts} failed: {last}")
        if attempt < attempts:
            time.sleep(retry_sleep_s)
    raise last


_SCENARIO = "infer"  # set by _main after arg parsing; read by the handler
_DEVICE_NOTE = None  # "unavailable" after a device-probe fallback


def emit(line: dict) -> None:
    """Print the driver's one JSON line, stamped with the device state —
    a CPU-fallback round must say so (``"device": "unavailable"``), so
    its numbers can never masquerade as on-chip evidence."""
    if _DEVICE_NOTE is not None:
        line.setdefault("device", _DEVICE_NOTE)
    print(json.dumps(line), flush=True)


def device_fallback(exc: BaseException) -> str:
    """Device probe failed (hung tunnel, dead chip, contention that never
    cleared): fall back to the CPU backend instead of dying with a
    zero-information error artifact (round-5 VERDICT ask). Every
    subsequent measurement — in-process scenarios via the jax config,
    server subprocesses via TPU_ENGINE_PLATFORM — runs host-side, the
    partial artifact records ``device: "unavailable"``, and the final
    JSON line carries the same stamp."""
    log(f"device probe failed ({exc!r}); falling back to CPU-backend "
        "scenarios — artifact will carry device=unavailable")
    record_partial("device", "unavailable")
    os.environ["TPU_ENGINE_PLATFORM"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return "unavailable"


def main() -> int:
    try:
        rc = _main()
        # The run emitted its final line: the run-stamped partial is
        # redundant now (aborted runs keep theirs for forensics).
        cleanup_partial()
        return rc
    except Exception as exc:  # ALWAYS leave the driver one JSON line
        log(f"bench failed: {exc!r}")
        line = {
            "metric": "bench_error", "value": 0.0, "unit": "error",
            "vs_baseline": 0.0, "scenario": _SCENARIO,
            "error": repr(exc)[:500],
        }
        if _DEVICE_NOTE is not None:
            line["device"] = _DEVICE_NOTE
        # A wedge after N completed measurements must not zero them out:
        # attach whatever landed before the failure (also on disk at the
        # run-stamped partial path). Metadata-only partials (scenario/ts)
        # are
        # NOT attached — "partial" present must mean real numbers
        # survived, or the driver would read an empty run as evidence.
        if any(k not in ("scenario", "ts") for k in _PARTIAL):
            line["partial"] = _PARTIAL
        print(json.dumps(line), flush=True)
        return 1


def _main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=10_000)
    ap.add_argument("--threads", type=int, default=50)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--lanes", type=int, default=0,
                    help="serving lanes (0 = one per device)")
    ap.add_argument("--port", type=int, default=0,
                    help="use an already-running server on this port")
    ap.add_argument("--quick", action="store_true",
                    help="1000 requests / 20 threads smoke run")
    ap.add_argument("--cache-test", action="store_true",
                    help="reference cache-effectiveness A/B instead of load")
    ap.add_argument("--distinct", type=int, default=10,
                    help="distinct input vectors in the load (10 = reference "
                         "parity / ~99.7%% hits; large values force the miss "
                         "path)")
    ap.add_argument("--no-compute", action="store_true",
                    help="skip the device-compute (MFU) addendum after the "
                         "serving load")
    ap.add_argument("--scenario",
                    choices=["infer", "generate", "compute", "decode-ab",
                             "spec-ab", "spec-batch-ab", "mixed",
                             "prefill-mfu", "longctx",
                             "miss-sweep", "paged-ab", "mixed-ab",
                             "crash-ab", "drain-ab", "affinity-ab",
                             "overload-ab", "quant-ab", "disagg-ab",
                             "recurrent-ab", "tp-ab", "elastic-ab",
                             "fleet-prefix-ab", "unified-ab"],
                    default="infer")
    args = ap.parse_args()
    # In-process scenarios (compute / decode-ab) honor the same platform
    # override the serving CLI does (the axon plugin ignores JAX_PLATFORMS).
    platform = os.environ.get("TPU_ENGINE_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    global _SCENARIO
    _SCENARIO = args.scenario
    _PARTIAL.clear()  # never let a previous run's numbers masquerade
    record_partial("scenario", args.scenario)
    # Preflight the device — except in --port mode, where a live server
    # already holds the (exclusive) chip and a second jax.devices() would
    # false-negative against a healthy deployment. A failed probe no
    # longer kills the round: scenarios fall back to the CPU backend and
    # the artifact says device="unavailable" (host-side numbers beat a
    # zero-information error line).
    global _DEVICE_NOTE
    if args.port == 0:
        try:
            probe_device()
        except Exception as exc:
            _DEVICE_NOTE = device_fallback(exc)
            args.quick = True  # CPU-budget sizes for every scenario
    if args.quick:
        args.requests, args.threads = 1000, 20
    if (args.scenario in ("generate", "decode-ab", "spec-batch-ab")
            and args.model == "resnet50"):
        args.model = "gpt2"
    if args.scenario == "mixed" and args.model == "resnet50":
        args.model = "yolov8n"
    if (args.scenario in ("paged-ab", "mixed-ab", "spec-ab", "affinity-ab",
                          "overload-ab", "quant-ab", "disagg-ab",
                          "recurrent-ab", "tp-ab", "fleet-prefix-ab",
                          "unified-ab")
            and args.model == "resnet50"):
        args.model = "gpt2-small-test"
    if _DEVICE_NOTE is not None:
        # Host-side runs also downshift the model: a 124M-param decode
        # loop on CPU would wedge the very round the fallback rescues.
        args.model = {"gpt2": "gpt2-small-test",
                      "resnet50": "mlp"}.get(args.model, args.model)

    if args.scenario == "compute":
        # In-process, no HTTP: pure device-compute evidence. A CPU
        # fallback round shrinks the decode model too.
        dm = "gpt2-small-test" if _DEVICE_NOTE is not None else "gpt2"
        compute = run_compute_bench(model=args.model
                                    if args.model != "gpt2" else "resnet50")
        record_partial("compute", compute)
        decode = run_decode_compute(model=dm)
        record_partial("decode", decode)
        decode_f = run_decode_compute(model=dm, fused=True)
        record_partial("decode_fused", decode_f)
        # Named so the honest comparison is self-evident: the int8 arm is
        # fused, so its pair is decode_fused (NOT the chunked "decode" —
        # dividing by that would conflate the fusion win into int8's).
        decode_fq = run_decode_compute(model=dm, quantize=True, fused=True)
        record_partial("decode_fused_int8", decode_fq)
        log(json.dumps({"compute": compute, "decode": decode,
                        "decode_fused": decode_f,
                        "decode_fused_int8": decode_fq}, indent=2))
        emit({
            "metric": "device_compute", "value": compute["samples_per_s"],
            "unit": "samples/s", "vs_baseline": None,
            "mfu": compute["mfu"], "decode_tokens_per_s": decode["tokens_per_s"],
            "compute": compute, "decode": decode, "decode_fused": decode_f,
            "decode_fused_int8": decode_fq,
        })
        return 0

    if args.scenario == "decode-ab":
        result = run_decode_ab(model=args.model)
        record_partial("decode_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "decode_continuous_speedup",
            "value": result["continuous_speedup"], "unit": "x",
            "vs_baseline": None, "model": args.model, **result,
        })
        return 0

    if args.scenario == "spec-ab":
        # Continuous speculative decoding (--spec-k) vs the plain paged
        # scheduler, counter-based. The batch-lane bracket A/B moved to
        # --scenario spec-batch-ab.
        result = run_spec_continuous_ab(
            model=args.model, max_new=24 if args.quick else 96)
        record_partial("spec_continuous_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "spec_tokens_per_row_dispatch",
            "value": result["tokens_per_dispatch_ratio"], "unit": "x",
            "vs_baseline": 1.0, "model": args.model, **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "crash-ab":
        # Crash-tolerant streaming A/B: worker processes serve the tiny
        # registry model on the host backend (the kill is the variable
        # under test, not the chip).
        result = run_crash_ab(n_streams=8 if args.quick else 12)
        record_partial("crash_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "crash_stream_completion_rate",
            "value": result["failover_on"]["stream_completion_rate"],
            "unit": "fraction",
            "vs_baseline": result["failover_off"][
                "stream_completion_rate"],
            **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "drain-ab":
        # Live stream migration A/B: worker processes on the host
        # backend (the drain semantics are the variable under test, not
        # the chip).
        result = run_drain_ab(n_streams=8 if args.quick else 10)
        record_partial("drain_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "drain_migrated_reprefill_tokens",
            "value": result["migrate_on"]["reprefill_tokens_replayed"],
            "unit": "tokens",
            "vs_baseline": result["replay_off"][
                "reprefill_tokens_replayed"],
            **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "elastic-ab":
        # Elastic fleet A/B: in-process lanes on the host backend (the
        # capacity bill under a diurnal trace is the variable under
        # test, not the chip).
        result = run_elastic_ab(model=(args.model if args.model
                                       != "resnet50"
                                       else "gpt2-chaos-test"),
                                quick=args.quick)
        record_partial("elastic_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "elastic_lane_seconds_ratio",
            "value": result["lane_seconds_ratio"], "unit": "x",
            "vs_baseline": 1.0,
            "lane_seconds_saved": result["lane_seconds_saved"],
            **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "overload-ab":
        # Adaptive overload control A/B: in-process lanes on the host
        # backend (admission ordering and goodput under saturation are
        # the variables under test, not the chip).
        result = run_overload_ab(model=args.model, quick=args.quick)
        record_partial("overload_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "overload_interactive_goodput_gain",
            "value": result["interactive_goodput_gain"], "unit": "x",
            "vs_baseline": 1.5,
            **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "affinity-ab":
        # Prefix-affinity routing + host-tier offload A/B: in-process
        # lanes on the host backend (routing convergence and radix hit
        # ratios are the variables under test, not the chip).
        result = run_affinity_ab(model=args.model, quick=args.quick)
        record_partial("affinity_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "affinity_prefill_skip_gain",
            "value": result["skip_gain"], "unit": "x",
            "vs_baseline": 2.0,
            "ttft_p99_on_ms": result["affinity_on"]["ttft_p99_ms"],
            "ttft_p99_off_ms": result["affinity_off"]["ttft_p99_ms"],
            **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "fleet-prefix-ab":
        # Fleet prefix tier A/B: in-process lanes on the host backend
        # (directory convergence and splice accounting are the
        # variables under test, not the chip).
        result = run_fleet_prefix_ab(model=args.model, quick=args.quick)
        record_partial("fleet_prefix_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "fleet_prefix_skip_gain",
            "value": result["skip_gain"], "unit": "x",
            "vs_baseline": 2.0,
            "remote_skipped_tokens":
                result["fetch_on"]["remote_skipped_tokens"],
            **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "unified-ab":
        # Unified stateless serving A/B: in-process arms on the host
        # backend by default (the variable under test is lane
        # coordination, not the chip); the on-chip campaign's `unified`
        # stage reruns it on the device.
        kw = {}
        if args.quick:
            kw = dict(n_generate=4, n_score=8, max_new=8,
                      model_kwargs={}, repeats=1)
        result = run_unified_ab(model=args.model, **kw)
        record_partial("unified_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "unified_score_p99_speedup",
            "value": result["score_p99_speedup"], "unit": "x",
            "vs_baseline": 1.0,
            "generate_p99_speedup": result["generate_p99_speedup"],
            **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "spec-batch-ab":
        result = run_spec_ab(model=args.model)
        record_partial("spec_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "speculative_speedup_upper",
            "value": result["self_draft"]["speedup_vs_plain"], "unit": "x",
            "vs_baseline": None, "model": args.model, **result,
        })
        return 0

    if args.scenario == "prefill-mfu":
        model = args.model if args.model != "resnet50" else "gpt2"
        result = run_prefill_mfu(model=model,
                                 batch=2 if args.quick else 8,
                                 seq=64 if args.quick else 1024,
                                 iters=3 if args.quick else 10)
        record_partial("prefill_mfu", result)
        log(json.dumps(result, indent=2))
        # `value` must stay numeric for the driver; mfu is None when cost
        # analysis or the chip's peak table is unavailable (CPU smoke).
        value, unit = result["mfu"], "fraction_of_peak"
        if value is None:
            value, unit = result["prefill_tokens_per_s"], "tokens/s"
        emit({
            "metric": "prefill_mfu", "value": value,
            "unit": unit, "vs_baseline": None, **result,
        })
        return 0

    if args.scenario == "longctx":
        model = args.model if args.model != "resnet50" else "gpt2"
        result = run_longcontext_prefill(
            model=model, seqs=(32, 64) if args.quick else (4096, 8192),
            xla_arm_max_seq=64 if args.quick else 4096)
        record_partial("longcontext_prefill", result)
        log(json.dumps(result, indent=2))
        top = max(int(k.split("_S")[1]) for k in result
                  if k.startswith("flash_S"))
        emit({
            "metric": "longcontext_prefill_tokens_per_s",
            "value": result[f"flash_S{top}"]["prefill_tokens_per_s"],
            "unit": "tokens/s", "vs_baseline": None, **result,
        })
        return 0

    if args.scenario == "miss-sweep":
        result = run_miss_path_sweep(
            model="mlp" if args.quick else args.model,
            depths=(4,) if args.quick else (4, 8, 16),
            n_requests=300 if args.quick else 3000,
            n_threads=8 if args.quick else args.threads)
        record_partial("miss_path_sweep", result)
        log(json.dumps(result, indent=2))
        best = max((v["throughput_req_s"], k) for k, v in result.items()
                   if k.startswith("depth"))
        emit({
            "metric": "miss_path_throughput",
            "value": best[0], "unit": "req/s", "best_depth": best[1],
            "vs_baseline": round(best[0] / BASELINE_REQ_S, 3), **result,
        })
        return 0

    if args.scenario == "paged-ab":
        result = run_paged_ab(
            model=args.model,
            n_requests=8 if args.quick else 16,
            max_new=48 if args.quick else 96)
        record_partial("paged_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "paged_kv_capacity_gain",
            "value": result["capacity_gain"], "unit": "x",
            "vs_baseline": None, "model": args.model,
            "prefill_token_savings_frac":
                result["prefill_token_savings_frac"], **result,
        })
        return 0

    if args.scenario == "quant-ab":
        result = run_quant_ab(
            model=args.model,
            n_requests=12 if args.quick else 24,
            max_new=48 if args.quick else 96)
        record_partial("quant_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "kv_quant_capacity_gain",
            "value": result["capacity_gain"], "unit": "x",
            "vs_baseline": None, "model": args.model, **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "recurrent-ab":
        result = run_recurrent_ab(att_model=args.model, quick=args.quick)
        record_partial("recurrent_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "recurrent_state_capacity_gain",
            "value": result["capacity_gain_at_longest"], "unit": "x",
            "vs_baseline": None, "model": args.model, **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "tp-ab":
        result = run_tp_ab(model=args.model, quick=args.quick)
        record_partial("tp_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "tp_peak_rows_gain",
            "value": result["peak_rows_gain"], "unit": "x",
            "vs_baseline": None, "model": args.model, **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "disagg-ab":
        result = run_disagg_ab(model=args.model, quick=args.quick)
        record_partial("disagg_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "disagg_itl_p99_speedup",
            "value": result["itl_p99_speedup"], "unit": "x",
            "vs_baseline": None, "model": args.model, **result,
        })
        return 0 if result["checks_passed"] else 1

    if args.scenario == "mixed-ab":
        result = run_mixed_ab(
            model=args.model,
            n_short=8 if args.quick else 12,
            n_long=2 if args.quick else 4,
            max_new=24 if args.quick else 40,
            long_prompt_len=120 if args.quick else 440,
            max_seq=128 if args.quick else 512,
            prefill_chunk=64 if args.quick else 256,
            model_kwargs={} if args.quick else None)
        record_partial("mixed_ab", result)
        log(json.dumps(result, indent=2))
        emit({
            "metric": "mixed_step_itl_p99_speedup",
            "value": result["itl_p99_speedup"], "unit": "x",
            "vs_baseline": None, "model": args.model, **result,
        })
        return 0 if result["checks_passed"] else 1

    proc = None
    port = args.port
    try:
        if port == 0:
            port, proc = launch_ready(args.model, args.lanes,
                                      mixed=args.scenario == "mixed")
        log(f"waiting for server on :{port} ...")
        wait_ready(port, proc=proc)

        if args.scenario == "mixed":
            result = run_mixed_shape_bench(port)
            record_partial("mixed", result)
            log(json.dumps(result, indent=2))
            result.update(scrape_stats(port))
            emit({
                "metric": "mixed_shape_throughput",
                "value": result["throughput_req_s"], "unit": "req/s",
                "vs_baseline": None, "model": args.model, **result,
            })
            return 0 if result["failed"] == 0 else 1

        if args.cache_test:
            result = run_cache_test(port)
            record_partial("cache_test", result)
            log(json.dumps(result, indent=2))
            emit({
                "metric": "cache_speedup", "value": result["speedup"],
                "unit": "x", "vs_baseline": None, "model": args.model,
                **result,
            })
            return 0

        if args.scenario == "generate":
            result = run_generate_bench(port)
            record_partial("generate", result)
            log(json.dumps(result, indent=2))
            emit({
                "metric": "decode_throughput", "value": result["tokens_per_s"],
                "unit": "tokens/s", "vs_baseline": None, "model": args.model,
                **result,
            })
            return 0 if result["failed"] == 0 else 1

        log("server ready; warmup pass (misses populate the cache) ...")
        warm = LoadGen(port, 20, 4)
        warm.run()

        log(f"benchmark: {args.requests} requests, {args.threads} threads, "
            f"{args.distinct} distinct inputs")
        gen = LoadGen(port, args.requests, args.threads,
                      distinct_inputs=args.distinct)
        result = gen.run()
        result.update(scrape_stats(port))
        record_partial("serving", result)
        log(json.dumps(result, indent=2))

        # Miss-heavy companion load (VERDICT r1 "bench workload hides the
        # engine"): same wire, every input distinct — no cache, every
        # request batches onto the device.
        miss = None
        if args.distinct == 10 and not args.quick:
            n_miss = max(1000, args.requests // 5)
            log(f"miss-path load: {n_miss} distinct requests ...")
            miss = LoadGen(port, n_miss, args.threads,
                           distinct_inputs=n_miss).run()
            miss = {
                "throughput_req_s": miss["throughput_req_s"],
                "p50_ms": miss["latency_ms"]["p50"],
                "p99_ms": miss["latency_ms"]["p99"],
                "success_rate": round(miss["success_rate"], 4),
            }
            record_partial("miss_path", miss)
            log(json.dumps({"miss_path": miss}, indent=2))

        # Per-stage latency attribution from the tracing layer (queue
        # wait vs device compute etc.) — scraped before the server stops.
        trace_stages = scrape_trace_stages(port)
        if trace_stages is not None:
            record_partial("trace_stages", trace_stages)
            log(json.dumps({"trace_stages": trace_stages}, indent=2))

        # Free the chip before the in-process compute addendum.
        if proc is not None:
            stop_server(proc)
            proc = None

        compute = decode = decode_fused = None
        if not args.no_compute:
            try:
                compute = run_compute_bench()
                record_partial("compute", compute)
                log(json.dumps({"compute": compute}, indent=2))
                decode = run_decode_compute()
                record_partial("decode", decode)
                log(json.dumps({"decode": decode}, indent=2))
                decode_fused = run_decode_compute(fused=True)
                record_partial("decode_fused", decode_fused)
                log(json.dumps({"decode_fused": decode_fused}, indent=2))
            except Exception as exc:
                log(f"compute addendum failed: {exc}")

        line = {
            "metric": "serving_throughput",
            "value": result["throughput_req_s"],
            "unit": "req/s",
            "vs_baseline": round(result["throughput_req_s"] / BASELINE_REQ_S, 3),
            "model": args.model,
            "requests": args.requests,
            "threads": args.threads,
            "distinct_inputs": args.distinct,
            "success_rate": round(result["success_rate"], 4),
            "p50_ms": result["latency_ms"]["p50"],
            "p99_ms": result["latency_ms"]["p99"],
            "cache_hit_rate": result.get("cache_hit_rate"),
            "avg_batch_size": result.get("avg_batch_size"),
        }
        if miss is not None:
            line["miss_path"] = miss
        if trace_stages is not None:
            line["trace_stages"] = trace_stages
        if compute is not None:
            line["compute"] = {k: compute[k] for k in
                               ("samples_per_s", "device_samples_per_s",
                                "device_step_ms", "e2e_step_ms",
                                "host_overhead_ms", "mfu",
                                "achieved_tflops", "device_kind") if k in compute}
        if decode is not None:
            line["decode"] = {k: decode[k] for k in
                              ("tokens_per_s", "decode_mfu") if k in decode}
        if decode_fused is not None:
            line["decode_fused"] = {
                k: decode_fused[k] for k in ("tokens_per_s", "decode_mfu")
                if k in decode_fused}
        emit(line)
        return 0 if result["success_rate"] > 0.99 else 1
    finally:
        stop_server(proc)


if __name__ == "__main__":
    sys.exit(main())
